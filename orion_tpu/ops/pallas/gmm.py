"""Grouped expert matmul (gmm) — the dropless-MoE hot path as ONE Mosaic
kernel (VERDICT r3 #3a: the ragged_dot+sort formulation cost the dropless
path 14.3% vs capacity dispatch at the 1.3B operating point).

Contract: ``gmm(x, w, group_sizes, tile_rows)`` computes
``y[i] = x[i] @ w[g(i)]`` where rows of ``x`` are laid out in
TILE-ALIGNED expert segments: the caller pads each expert's row block up
to a multiple of ``tile_rows`` (models/moe.py::_dropless does this with
its counting-sort scatter), so every ``tile_rows``-row tile belongs to
exactly ONE expert. The tile->expert table is scalar-prefetched
(pltpu.PrefetchScalarGridSpec) and drives the weight BlockSpec's index
map — the kernel is then a plain MXU matmul per (row-tile, out-tile)
with zero dynamic control flow inside the body.

Why this beats ragged_dot here: XLA's ragged_dot must handle arbitrary
group boundaries inside a tile (masked multi-expert accumulation);
tile-aligning the segments moves that irregularity OUT of the kernel
into a cheap one-time scatter (<= E*(tile_rows-1) wasted rows, ~2% at
the flagship shapes) and leaves Mosaic a dense, perfectly-tiled matmul
stream.

Backward: dx rides the same kernel against swapaxes(w, 1, 2); dw is a
second kernel accumulating x_tile^T @ dy_tile into the expert's [d, h]
block — tiles of one expert are consecutive, so the output block is
revisited consecutively (the Pallas TPU revisiting rule) with a
first-tile zero-init.

reference: none — BASELINE.json names no MoE; this kernel exists for the
framework's own dropless formulation (reference checkout never mounted,
SURVEY.md §0).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from orion_tpu.ops.pallas.causal_dot import _sds  # vma-carrying out_shape:
# lets these kernels compose with shard_map(check_vma=True) bodies (the
# dropless-ep gmm region, models/moe.py::_dropless_ep_gmm)

Array = jax.Array


def _vma_union_like(a: Array, b: Array) -> Array:
    """Zero-size value carrying the UNION of two operands' varying-mesh-
    axes types (e.g. x varying over data axes, w varying over ep): the
    product's vma is the union, and the slice keeps it costless."""
    return a.reshape(-1)[:1] * b.reshape(-1)[:1].astype(a.dtype)


# dw-kernel output tile (see _gmm_bwd): chip-swept at the flagship
# dropless shapes (exp_r5gmm.py -> R5GMM.jsonl)
_DW_BLOCK_D = 1024
_DW_BLOCK_H = 1024


def tile_expert_table(group_sizes: Array, n_tiles: int, tile_rows: int) -> Array:
    """[n_tiles] int32: owning expert of each row tile, given TILE-ALIGNED
    segment sizes (every entry of ``group_sizes`` divisible by tile_rows;
    trailing tiles beyond the last segment map to the last expert — their
    rows are caller padding and never gathered back)."""
    starts = jnp.cumsum(group_sizes) - group_sizes  # [E] segment starts
    rows = jnp.arange(n_tiles, dtype=jnp.int32) * tile_rows
    return (
        jnp.sum(rows[:, None] >= starts[None, :], axis=1).astype(jnp.int32) - 1
    ).clip(0)


def _fwd_kernel(te_ref, x_ref, w_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        x_ref[...], w_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(o_ref.dtype)


def _gmm_call(x, w, tile_expert, tile_rows, block_h, interpret):
    m, d = x.shape
    e, _, h = w.shape
    nt, nh = m // tile_rows, -(-h // block_h)
    hp = nh * block_h
    if hp != h:
        w = jnp.pad(w, ((0, 0), (0, 0), (0, hp - h)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        # h-tiles OUTER, row-tiles INNER: consecutive same-expert row
        # tiles then hit the SAME weight block index, and Mosaic skips the
        # reload — weight HBM traffic is O(E·d·h) per h-sweep instead of
        # O(n_tiles·d·block_h) (measured: the (nt, nh) order re-streamed
        # 4.3GB of expert weights per gmm at the 1.3B MoE shapes)
        grid=(nh, nt),
        in_specs=[
            pl.BlockSpec((tile_rows, d), lambda j, i, te: (i, 0)),
            pl.BlockSpec((1, d, block_h), lambda j, i, te: (te[i], 0, j)),
        ],
        out_specs=pl.BlockSpec((tile_rows, block_h), lambda j, i, te: (i, j)),
    )
    out = pl.pallas_call(
        _fwd_kernel,
        out_shape=_sds((m, hp), x.dtype, _vma_union_like(x, w)),
        grid_spec=grid_spec,
        interpret=interpret,
    )(tile_expert, x, w)
    return out[:, :h] if hp != h else out


def _dw_kernel(te_ref, x_ref, g_ref, dw_ref):
    i = pl.program_id(2)
    first = jnp.logical_or(i == 0, te_ref[i] != te_ref[jnp.maximum(i - 1, 0)])

    @pl.when(first)
    def _():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    dw_ref[...] += jax.lax.dot_general(
        x_ref[...], g_ref[...],
        (((0,), (0,)), ((), ())),  # [tm, bd]^T @ [tm, bh] -> [bd, bh]
        preferred_element_type=jnp.float32,
    )[None]


def _dw_call(x, g, tile_expert, n_experts, tile_rows, block_d, block_h,
             interpret):
    """dw[e] = sum over e's rows of x^T g, BOTH output dims tiled: the
    2D-grid form either blew the VMEM stack (full-d blocks at d=5504) or,
    at small block_h, re-streamed the x rows h/block_h ~= 43 times —
    ~13GB of HBM per MoE layer's backward at the 1.3B shapes. Tiling d
    and h at 512 keeps blocks ~1MB and total traffic ~2GB."""
    m, d = x.shape
    h = g.shape[1]
    nt = m // tile_rows
    nd, nh = -(-d // block_d), -(-h // block_h)
    if nd * block_d != d:
        x = jnp.pad(x, ((0, 0), (0, nd * block_d - d)))
    if nh * block_h != h:
        g = jnp.pad(g, ((0, 0), (0, nh * block_h - h)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        # row-tiles INNER: each expert's dw block is revisited over
        # consecutive iterations (the Pallas revisiting rule the
        # accumulation relies on)
        grid=(nd, nh, nt),
        in_specs=[
            pl.BlockSpec((tile_rows, block_d), lambda jd, jh, i, te: (i, jd)),
            pl.BlockSpec((tile_rows, block_h), lambda jd, jh, i, te: (i, jh)),
        ],
        out_specs=pl.BlockSpec(
            (1, block_d, block_h), lambda jd, jh, i, te: (te[i], jd, jh)
        ),
    )
    dw = pl.pallas_call(
        _dw_kernel,
        out_shape=_sds(
            (n_experts, nd * block_d, nh * block_h), jnp.float32,
            _vma_union_like(x, g),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(tile_expert, x, g)
    return dw[:, :d, :h]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def gmm(
    x: Array,
    w: Array,
    group_sizes: Array,
    tile_rows: int = 128,
    block_h: int = 512,
    interpret: bool = False,
) -> Array:
    """y[i] = x[i] @ w[g(i)] over tile-aligned expert segments.

    x: [M, d] rows sorted into expert segments, each segment a multiple of
       ``tile_rows`` (M divisible by tile_rows); caller-padded rows compute
       garbage against their segment's expert and must be dropped on
       gather-back.
    w: [E, d, h] stacked expert weights; group_sizes: [E] int32
       tile-aligned segment sizes summing to <= M.
    """
    out, _ = _gmm_fwd(x, w, group_sizes, tile_rows, block_h, interpret)
    return out


def _gmm_fwd(x, w, group_sizes, tile_rows, block_h, interpret):
    m = x.shape[0]
    assert m % tile_rows == 0, (m, tile_rows)
    te = tile_expert_table(group_sizes, m // tile_rows, tile_rows)
    wc = w.astype(x.dtype)
    out = _gmm_call(x, wc, te, tile_rows, block_h, interpret)
    # residuals must be jax types: a zero-size array carries w's dtype
    return out, (x, wc, te, jnp.zeros((0,), w.dtype))


def _gmm_bwd(tile_rows, block_h, interpret, res, dy):
    x, wc, te, w_dtype_probe = res
    w_dtype = w_dtype_probe.dtype
    e = wc.shape[0]
    dyc = dy.astype(x.dtype)
    # dx[i] = dy[i] @ w[g(i)]^T — the same kernel against transposed stacks
    dx = _gmm_call(
        dyc, jnp.swapaxes(wc, 1, 2), te, tile_rows, block_h, interpret
    ).astype(x.dtype)
    # dw tiles are independent of the fwd/dx block_h. The dw stream
    # traffic is nd*nh*(M*(block_d+block_h)) — x re-read nh times, dy
    # re-read nd times — so bigger blocks directly cut the backward's
    # HBM bill; the (1, bd, bh) fp32 dw block is the VMEM bound
    # (1024x1024 = 4MB, well under the 16MB stack — the r4 OOM note was
    # the FWD kernel's [d, block_h] weight blocks, not these).
    # R5GMM.jsonl: dw-block sweep at the flagship dropless shapes.
    dw = _dw_call(
        x, dyc, te, e, tile_rows,
        min(_DW_BLOCK_D, x.shape[1]), min(_DW_BLOCK_H, dy.shape[1]),
        interpret,
    )
    # an expert with ZERO tiles never has its dw block written — the out
    # buffer holds uninitialized memory there, so mask by presence (pad
    # rows inside real tiles are zeros and need no mask)
    present = jnp.zeros((e,), bool).at[te].set(True)
    dw = jnp.where(present[:, None, None], dw, 0.0).astype(w_dtype)
    return dx, dw, None


gmm.defvjp(_gmm_fwd, _gmm_bwd)


def pad_group_sizes(counts: Array, tile_rows: int) -> Tuple[Array, Array]:
    """(tile-aligned segment sizes, exclusive segment starts) for raw
    per-expert row counts."""
    seg = -(-counts // tile_rows) * tile_rows
    starts = jnp.cumsum(seg) - seg
    return seg.astype(jnp.int32), starts.astype(jnp.int32)


__all__ = ["gmm", "pad_group_sizes", "tile_expert_table"]
