"""Pallas TPU fused Adafactor: the whole optimizer update — factored
second-moment stats, update clipping, learning rate, finite-guard, and the
parameter write — in three streaming passes per weight matrix.

Why: the reference framework ships fused CUDA optimizers (reference:
BASELINE.json north_star's torch/CUDA training stack; checkout never
mounted — SURVEY.md §0); this is the TPU-native equivalent, built because
the optimizer region profiled at ~120ms of the 1.3B flagship step.

Measured outcome (v5e chip, b12 x skip6 flagship step — an HONEST
NEGATIVE, kept as an option): 1778ms/step fused vs 1755ms for the optax
chain. XLA already fuses the optax transforms close to the traffic floor
(the q = s^2 g^2 + eps pass fuses with BOTH factored reduces, and the
update pass rides the apply), while this version pays ~450 un-fusable
custom-call launches (3 kernels x ~150 matrices). The default optimizer
therefore stays "adafactor"; "adafactor_fused" remains available, exact,
and tested — the economics may flip at other param/token ratios.

Semantics are bit-compatible with the repo's optax configuration
(``optax.adafactor(sched, min_dim_size_to_factor=128,
multiply_by_parameter_scale=False)`` — training/trainer.py) composed with
the Trainer's caller-side clip/finite fusion:

    q          = (scale * g)^2 + eps            # scale folds clip + guard
    v_row      = d_t * v_row + (1 - d_t) * mean(q, axis=d0)
    v_col      = d_t * v_col + (1 - d_t) * mean(q, axis=d1)
    u          = scale * g * (v_row / mean(v_row))^-1/2 * v_col^-1/2
    u          = u / max(1, rms(u) / threshold)  # update clipping
    p          = p - lr * u                      # skipped when non-finite
    d_t        = 1 - (count + 1)^-0.8

Three passes per factored matrix (the RMS term forces the split — rms(u)
needs the completed v_row/v_col, and the apply needs rms(u)):
  A: read G        -> axis-0 sums [n], axis-1 sums [m]      (stats)
  B: read G        -> sum(u^2) scalar                        (clip RMS)
  C: read G, P     -> write P' (aliased in-place)            (apply)
G is read 3x and P 1x+1w ≈ 25GB at 1.3B — the streaming floor. Between
passes, the EMA/factor math runs on [m]+[n] vectors in XLA (trivial).
Non-factored leaves (1D / small / tile-misaligned) take an exact jnp
replica of the optax formulas — negligible bytes.

Single-device meshes only: a Mosaic custom call cannot be auto-partitioned
by GSPMD (parallel/kernel_shard.py), and sharding the optimizer adds
psums over the factored vectors — Trainer REJECTS this option on
multi-device meshes (no silent fallback: the opt_state checkpoint pytree
must not depend on mesh size); configure optimizer="adafactor" there.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

Array = jax.Array

_DECAY = 0.8
_EPS = 1e-30
_CLIP = 1.0
_MIN_FACTOR_DIM = 128


class FusedAdafactorState(NamedTuple):
    """Same per-leaf shapes (and memory) as optax's FactoredState."""

    count: Array
    v_row: Any
    v_col: Any
    v: Any


def _factored_dims(shape) -> Optional[Tuple[int, int]]:
    """optax._src.factorized._factored_dims (factored=True, min 128):
    (d1, d0) = indices of the second-largest and largest axes."""
    if len(shape) < 2:
        return None
    sorted_dims = np.argsort(shape)
    if shape[sorted_dims[-2]] < _MIN_FACTOR_DIM:
        return None
    return int(sorted_dims[-2]), int(sorted_dims[-1])


_MIN_KERNEL_ELEMS = 1 << 20  # tests lower this to force the kernel path


def _kernel_ok(shape, dtype=jnp.float32) -> bool:
    """2D fp32, tile aligned (rows % 8, lanes % 128), big enough to matter.
    Non-fp32 leaves (param_dtype="bfloat16") take the jnp path, which casts
    to f32 — the kernels' g*g and tile shapes assume fp32."""
    return (
        len(shape) == 2
        and dtype == jnp.float32
        and shape[0] % 8 == 0
        and shape[1] % 128 == 0
        and shape[0] * shape[1] >= _MIN_KERNEL_ELEMS
    )


def _row_block(m: int, n: int) -> int:
    """Largest divisor of m (multiple of 8) keeping each [bm, n] fp32 block
    ~<=1MB: the apply kernel holds three such blocks double-buffered, and
    Mosaic's scoped-vmem stack is 16MB (hit at [32000, 2048] with bm=400)."""
    cap = min(m, 512, max(8, (1 << 20) // (4 * n) // 8 * 8))
    best = 8
    for bm in range(8, cap + 1, 8):
        if m % bm == 0:
            best = bm
    return best


# -- kernels ----------------------------------------------------------------


def _sums_kernel(eps: float, s2_ref, g_ref, s0_ref, s1_ref):
    """Per row-tile: q = s^2 g^2 + eps; accumulate axis-0 sums, write
    axis-1 sums."""
    i = pl.program_id(0)
    g = g_ref[...]
    q = g * g * s2_ref[0, 0] + eps
    s1_ref[...] = q.sum(axis=1, keepdims=True)

    @pl.when(i == 0)
    def _init():
        s0_ref[...] = jnp.zeros_like(s0_ref)

    s0_ref[...] += q.sum(axis=0, keepdims=True)


def _rms_kernel(g_ref, r_ref, c_ref, acc_ref):
    """sum(u^2) for the update-clipping RMS; scale folded into r."""
    i = pl.program_id(0)
    u = g_ref[...] * r_ref[...] * c_ref[...]

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += (u * u).sum().reshape(1, 1)


def _apply_kernel(f_ref, g_ref, r_ref, c_ref, p_ref, out_ref):
    """p' = p + g*r*c, or p untouched on a non-finite step (r folds
    -lr * clip * scale)."""
    p = p_ref[...]
    u = g_ref[...] * r_ref[...] * c_ref[...]
    out_ref[...] = jnp.where(f_ref[0, 0] > 0, p + u, p)


def _pallas_sums(g: Array, s2: Array, eps: float, interpret: bool):
    m, n = g.shape
    bm = _row_block(m, n)
    s0, s1 = pl.pallas_call(
        functools.partial(_sums_kernel, eps),
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((m, 1), jnp.float32),
        ],
        interpret=interpret,
    )(s2.reshape(1, 1), g)
    return s0.reshape(n), s1.reshape(m)


def _pallas_rms(g: Array, r: Array, c: Array, interpret: bool) -> Array:
    m, n = g.shape
    bm = _row_block(m, n)
    acc = pl.pallas_call(
        _rms_kernel,
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        interpret=interpret,
    )(g, r.reshape(m, 1), c.reshape(1, n))
    return acc.reshape(())


def _pallas_apply(g: Array, p: Array, r: Array, c: Array, finite: Array,
                  interpret: bool) -> Array:
    m, n = g.shape
    bm = _row_block(m, n)
    return pl.pallas_call(
        _apply_kernel,
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), p.dtype),
        input_output_aliases={4: 0},
        interpret=interpret,
    )(finite.astype(jnp.float32).reshape(1, 1), g, r.reshape(m, 1),
      c.reshape(1, n), p)


# -- per-leaf update --------------------------------------------------------


def _leaf_update(g, p, v_row, v_col, v, *, decay_t, lr, scale, finite,
                 eps, clip, use_kernel, interpret):
    """One parameter tensor. Returns (new_p, new_v_row, new_v_col, new_v).

    State selects (keep old on a non-finite step) happen here on the small
    stat tensors; in the kernel path the param select rides inside the
    apply kernel."""
    dims = _factored_dims(g.shape)
    keep = lambda new, old: jnp.where(finite, new, old)  # noqa: E731

    if dims is None:
        # exact optax non-factored path (small leaves: norm scales, biases)
        q = (scale * g) ** 2 + eps
        new_v = (decay_t * v + (1.0 - decay_t) * q).astype(p.dtype)
        u = scale * g * jax.lax.rsqrt(new_v)
        if clip:
            u = u / jnp.maximum(1.0, jnp.sqrt(jnp.mean(u * u)) / clip)
        new_p = jnp.where(finite, p - lr * u, p)
        return new_p, v_row, v_col, keep(new_v, v)

    d1, d0 = dims
    fast = use_kernel and _kernel_ok(g.shape, g.dtype) and p.dtype == g.dtype
    if not fast:
        # exact optax factored path in jnp, any ndim (e.g. [E, D, H] MoE
        # expert stacks) — the parity reference for the kernels below
        q = (scale * g.astype(jnp.float32)) ** 2 + eps
        new_v_row = (decay_t * v_row
                     + (1.0 - decay_t) * q.mean(axis=d0)).astype(p.dtype)
        new_v_col = (decay_t * v_col
                     + (1.0 - decay_t) * q.mean(axis=d1)).astype(p.dtype)
        reduced_d1 = d1 - 1 if d1 > d0 else d1
        row_col_mean = jnp.mean(new_v_row, axis=reduced_d1, keepdims=True)
        row_factor = jax.lax.rsqrt(new_v_row / row_col_mean)
        col_factor = jax.lax.rsqrt(new_v_col)
        u = (scale * g.astype(jnp.float32)
             * jnp.expand_dims(row_factor, d0)
             * jnp.expand_dims(col_factor, d1))
        if clip:
            u = u / jnp.maximum(1.0, jnp.sqrt(jnp.mean(u * u)) / clip)
        new_p = jnp.where(finite, (p - lr * u).astype(p.dtype), p)
        return new_p, keep(new_v_row, v_row), keep(new_v_col, v_col), v

    m, n = g.shape
    s2 = (scale * scale).astype(jnp.float32)
    sum0, sum1 = _pallas_sums(g, s2, eps, interpret)  # [n], [m]

    # optax: v_row = mean over axis d0, v_col = mean over axis d1
    mean_d0 = (sum1 / n) if d0 == 1 else (sum0 / m)   # shape: del(d0)
    mean_d1 = (sum0 / m) if d1 == 0 else (sum1 / n)   # shape: del(d1)
    new_v_row = (decay_t * v_row + (1.0 - decay_t) * mean_d0).astype(p.dtype)
    new_v_col = (decay_t * v_col + (1.0 - decay_t) * mean_d1).astype(p.dtype)
    row_factor = jax.lax.rsqrt(new_v_row / jnp.mean(new_v_row))
    col_factor = jax.lax.rsqrt(new_v_col)

    # u[i,j] = scale * g[i,j] * row_factor[expand d0] * col_factor[expand d1]
    # -> express as g * rvec[m] * cvec[n]
    if d0 == 1:  # row_factor along axis0 [m], col_factor along axis1 [n]
        rvec, cvec = row_factor, col_factor
    else:        # row_factor along axis1 [n], col_factor along axis0 [m]
        rvec, cvec = col_factor, row_factor
    rvec_s = rvec.astype(jnp.float32) * scale
    cvec32 = cvec.astype(jnp.float32)

    sum_u2 = _pallas_rms(g, rvec_s, cvec32, interpret)
    kappa = -lr
    if clip:
        kappa = kappa / jnp.maximum(1.0, jnp.sqrt(sum_u2 / (m * n)) / clip)
    new_p = _pallas_apply(g, p, rvec_s * kappa, cvec32, finite, interpret)
    return new_p, keep(new_v_row, v_row), keep(new_v_col, v_col), v


# -- public API -------------------------------------------------------------


def init(params) -> FusedAdafactorState:
    """Mirror of optax.adafactor's state shapes (FactoredState)."""

    def _init(p):
        dims = _factored_dims(p.shape)
        if dims is not None:
            d1, d0 = dims
            vr = jnp.zeros(np.delete(p.shape, d0), p.dtype)
            vc = jnp.zeros(np.delete(p.shape, d1), p.dtype)
            return vr, vc, jnp.zeros((1,), p.dtype)
        return (jnp.zeros((1,), p.dtype), jnp.zeros((1,), p.dtype),
                jnp.zeros(p.shape, p.dtype))

    leaves = jax.tree.map(_init, params)
    pick = lambda i: jax.tree.map(  # noqa: E731
        lambda t: t[i], leaves, is_leaf=lambda t: isinstance(t, tuple)
    )
    return FusedAdafactorState(
        count=jnp.zeros((), jnp.int32),
        v_row=pick(0), v_col=pick(1), v=pick(2),
    )


def apply_updates(
    grads, params, state: FusedAdafactorState, *, lr, scale, finite,
    decay_rate: float = _DECAY, eps: float = _EPS,
    clipping_threshold: Optional[float] = _CLIP,
    backend: str = "auto",
):
    """(new_params, new_state). ``scale`` folds the caller's grad clip and
    finite guard exactly like Trainer._train_step's safe_grads; ``finite``
    keeps params AND stats untouched on a bad step (the skip policy)."""
    if backend == "auto":
        try:
            plat = jax.devices()[0].platform
        except RuntimeError:
            plat = "cpu"
        backend = "pallas" if plat == "tpu" else "jnp"
    use_kernel = backend in ("pallas", "interpret")
    interpret = backend == "interpret"

    t = jnp.asarray(state.count + 1, jnp.float32)
    decay_t = 1.0 - t ** (-decay_rate)
    lr = jnp.asarray(lr, jnp.float32)
    scale = jnp.asarray(scale, jnp.float32)
    finite = jnp.asarray(finite)

    flat_g, treedef = jax.tree.flatten(grads)
    flat_p = treedef.flatten_up_to(params)
    flat_vr = treedef.flatten_up_to(state.v_row)
    flat_vc = treedef.flatten_up_to(state.v_col)
    flat_v = treedef.flatten_up_to(state.v)
    out_p, out_vr, out_vc, out_v = [], [], [], []
    for g, p, vr, vc, v in zip(flat_g, flat_p, flat_vr, flat_vc, flat_v):
        np_, nvr, nvc, nv = _leaf_update(
            g, p, vr, vc, v, decay_t=decay_t, lr=lr, scale=scale,
            finite=finite, eps=eps, clip=clipping_threshold,
            use_kernel=use_kernel, interpret=interpret,
        )
        out_p.append(np_)
        out_vr.append(nvr)
        out_vc.append(nvc)
        out_v.append(nv)
    new_state = FusedAdafactorState(
        # good-step count: the optax twin's counts live inside the state the
        # Trainer rolls back wholesale on a non-finite step, so a skipped
        # step must not advance decay_t / the lr schedule here either
        count=state.count + finite.astype(state.count.dtype),
        v_row=jax.tree.unflatten(treedef, out_vr),
        v_col=jax.tree.unflatten(treedef, out_vc),
        v=jax.tree.unflatten(treedef, out_v),
    )
    return jax.tree.unflatten(treedef, out_p), new_state
