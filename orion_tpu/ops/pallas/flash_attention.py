"""Pallas TPU flash attention: causal / bidirectional / sliding-window.

TPU-native replacement for the reference's CUDA softmax-attention path
(BASELINE.json north_star: LRA softmax configs and the 7B hybrid's
sliding-window softmax layers; the reference checkout was never mounted —
SURVEY.md §0). Online-softmax tiling: never materializes the T×T score
matrix, accumulates in fp32 VMEM scratch.

Forward:  grid (B·H, Tq/Bq, Tk/Bk), k-axis innermost (sequential on a TPU
core), scratch carries the running row-max m, row-sum l, and output
accumulator; finalized on the last k-block. Saves the log-sum-exp for the
backward as a [B·H, T, 1] column (the trailing unit dim keeps the block
shape legal under TPU (8,128) tiling).

Backward (custom VJP, two kernels — the standard flash decomposition):
    delta = rowsum(dO ⊙ O)                       (XLA, one fused reduce)
    dQ kernel (grid B·H × Tq/Bq × Tk/Bk):  P = exp(S − lse);
        dS = P ⊙ (dO Vᵀ − delta);  dQ += dS K · scale
    dK/dV kernel (grid B·H × Tk/Bk × Tq/Bq): same q-major (Bq, Bk) tile
        orientation — PᵀdO and dSᵀQ come out of dot_general by contracting
        the q dim, so no in-kernel transposes;  dV += PᵀdO;  dK += dSᵀQ·scale
Both recompute P from (q, k, lse) — O(T) memory, matmuls on the MXU.

``window=w`` = each query sees keys s ∈ (t−w, t]. Masks are structural
(computed from block indices + iota), so sliding-window skips every tile
outside the band — cost O(T·w), not O(T²).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from orion_tpu.ops.pallas.causal_dot import _sds  # vma-carrying out_shape:
# lets these kernels compose with shard_map(check_vma=True) bodies
# (parallel/kernel_shard.py, parallel/pipeline.py) the same way the
# causal_dot kernels do

Array = jax.Array

_NEG = -1e30


def _tile_mask(rows: Array, cols: Array, causal: bool, window: Optional[int],
               t_k: int, shift: int = 0, q_offset: int = 0):
    """Boolean (Bq, Bk) tile of the structural mask at absolute row/col ids.
    ``shift`` strengthens the causal bound to rows >= cols + shift:
    shift=1 is the STRICT triangle a striped ring block needs when the kv
    stripe's phase is ahead of the query stripe's (parallel/ring.py)."""
    m = cols < t_k  # mask out key padding
    rows = rows + q_offset
    if causal:
        m &= rows >= cols + shift
    if window is not None:
        m &= (rows - cols) < window
    return m


def _skip_tile(qi, ki, bq, bk, causal, window, shift: int = 0,
               q_offset: int = 0):
    """True if tile (qi, ki) is entirely masked (static-shape predicate)."""
    skip = jnp.bool_(False)
    if causal:
        # first key row past the last query it may attend to
        skip |= ki * bk > qi * bq + q_offset + (bq - 1) - shift
    if window is not None:
        # band entirely left of the tile
        skip |= (qi * bq + q_offset) - (ki * bk + bk - 1) >= window
    return skip


def _rowscol(qi, ki, bq, bk):
    rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return rows, cols


# benchmark switch (exp_r5swa.py): False restores the full quadratic grid
# so the clip-vs-mask delta is measurable on the SAME build
_BANDED_ENABLED = True


def _banded_ok(causal, window, shift, q_offset, t_q, t_k) -> bool:
    """Use the BANDED grid (VERDICT r4 #6 — clip, don't mask): the k sweep
    per q-tile covers only tiles intersecting the (window, causal) band
    via a qi-dependent BlockSpec index map. Cuts the swept area from
    O(T^2) grid steps to O(T*window) AND makes small block_k affordable —
    the boundary tiles' masked padding shrinks with bk, which the full
    quadratic grid couldn't exploit (its step count scaled with 1/bk over
    the WHOLE row). Plain single-shard swa only: the ring/halo callers
    (shift/q_offset) keep the classic grid, whose skip predicate already
    serves their offset geometry."""
    return (
        _BANDED_ENABLED
        and causal and window is not None and shift == 0 and q_offset == 0
        and t_q == t_k and window < t_k
    )


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _banded_base(qi, bq, bk, window):
    """First k-tile of query tile ``qi``'s band (may be negative near the
    sequence start — callers clip the fetch and skip the compute)."""
    return (qi * bq - window + 1) // bk


def _banded_nj(nq: int, bq: int, bk: int, window: int) -> int:
    """Grid extent of the banded k sweep: max tiles any q-tile's band
    touches (exact python max, not a bound — nq is at most thousands)."""
    m = 1
    for qi in range(nq):
        base = (qi * bq - window + 1) // bk
        m = max(m, (qi * bq + bq - 1) // bk - base + 1)
    return m


def _banded_q_nj(nk: int, bq: int, bk: int, window: int) -> int:
    """Grid extent of the banded q sweep (dk/dv kernel): max q-tiles any
    k-tile's band touches."""
    m = 1
    for ki in range(nk):
        base = (ki * bk) // bq
        m = max(m, (ki * bk + bk + window - 2) // bq - base + 1)
    return m


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, scale, causal, window, shift, q_offset, t_k, bq, bk, nk, banded,
    nk_real,
):
    qi, j = pl.program_id(1), pl.program_id(2)
    if banded:  # k-tile index is band-relative (swa clip, module docstring)
        ki = _banded_base(qi, bq, bk, window) + j
        oob = (ki < 0) | (ki >= nk_real)
    else:
        ki = j
        oob = jnp.bool_(False)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(jnp.logical_not(
        oob | _skip_tile(qi, ki, bq, bk, causal, window, shift, q_offset)
    ))
    def _():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (Bq, Bk)
        rows, cols = _rowscol(qi, ki, bq, bk)
        s = jnp.where(_tile_mask(rows, cols, causal, window, t_k, shift, q_offset), s, _NEG)

        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # (Bq, Bk) fp32
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            p, v_ref[0].astype(jnp.float32), preferred_element_type=jnp.float32
        )
        m_scr[:] = m_new

    @pl.when(j == nk - 1)
    def _():
        l = l_scr[:]
        safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (padding) -> 0
        o_ref[0] = (acc_scr[:] / safe).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(safe)  # (Bq, 1)


def _flash_fwd_flat(q, k, v, scale, causal, window, bq, bk, interpret, shift=0,
                    q_offset=0):
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    dv = v.shape[-1]
    pq, pk = (-t_q) % bq, (-t_k) % bk
    qp = jnp.pad(q, ((0, 0), (0, pq), (0, 0))) if pq else q
    kp = jnp.pad(k, ((0, 0), (0, pk), (0, 0))) if pk else k
    vp = jnp.pad(v, ((0, 0), (0, pk), (0, 0))) if pk else v
    nq, nk = qp.shape[1] // bq, kp.shape[1] // bk

    banded = _banded_ok(causal, window, shift, q_offset, t_q, t_k)
    if banded:
        grid_k = _banded_nj(nq, bq, bk, window)
        kvmap = lambda b, i, j: (  # noqa: E731
            b, jnp.clip(_banded_base(i, bq, bk, window) + j, 0, nk - 1), 0
        )
    else:
        grid_k = nk
        kvmap = lambda b, i, j: (b, j, 0)  # noqa: E731

    kern = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, window=window, shift=shift,
        q_offset=q_offset,
        t_k=t_k, bq=bq, bk=bk, nk=grid_k, banded=banded, nk_real=nk,
    )
    out, lse = pl.pallas_call(
        kern,
        grid=(bh, nq, grid_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), kvmap, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dv), kvmap, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, dv), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _sds((bh, nq * bq, dv), q.dtype, q),
            _sds((bh, nq * bq, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, dv), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :t_q, :], lse[:, :t_q, :]


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, scale, causal, window, shift, q_offset, t_k, bq, bk, nk, banded,
    nk_real,
):
    qi, j = pl.program_id(1), pl.program_id(2)
    if banded:
        ki = _banded_base(qi, bq, bk, window) + j
        oob = (ki < 0) | (ki >= nk_real)
    else:
        ki = j
        oob = jnp.bool_(False)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(jnp.logical_not(
        oob | _skip_tile(qi, ki, bq, bk, causal, window, shift, q_offset)
    ))
    def _():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        rows, cols = _rowscol(qi, ki, bq, bk)
        mask = _tile_mask(rows, cols, causal, window, t_k, shift, q_offset)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)  # lse: (Bq, 1)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        dq_scr[:] = dq_scr[:] + jnp.dot(
            ds, k_ref[0].astype(jnp.float32), preferred_element_type=jnp.float32
        )

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr,
    *, scale, causal, window, shift, q_offset, t_k, bq, bk, nq, banded,
    nq_real,
):
    ki, j = pl.program_id(1), pl.program_id(2)
    if banded:  # q-tile index is band-relative: q rows in [ki*bk, ki*bk+bk+w)
        qi = (ki * bk) // bq + j
        oob = qi >= nq_real
    else:
        qi = j
        oob = jnp.bool_(False)

    @pl.when(j == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(jnp.logical_not(
        oob | _skip_tile(qi, ki, bq, bk, causal, window, shift, q_offset)
    ))
    def _():
        # q-major (Bq, Bk) tile; k-side grads via contraction over the q dim
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        rows, cols = _rowscol(qi, ki, bq, bk)
        mask = _tile_mask(rows, cols, causal, window, t_k, shift, q_offset)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do_ref[0].astype(jnp.float32),
            dimension_numbers=(((0,), (0,)), ((), ())),  # Pᵀ dO
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q_ref[0].astype(jnp.float32),
            dimension_numbers=(((0,), (0,)), ((), ())),  # dSᵀ Q
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_flat(q, k, v, out, lse, g, scale, causal, window, bq, bk, interpret,
                    shift=0, dlse=None, q_offset=0):
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    dv = v.shape[-1]
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )  # (BH, Tq, 1)
    if dlse is not None:
        # lse cotangent (flash_attention_lse): dS_ij = P̂_ij (dP_ij − Δ_i +
        # dlse_i), since ∂lse_i/∂S_ij = P̂_ij — folds into the delta column,
        # so the kernels themselves are unchanged
        delta = delta - dlse.astype(jnp.float32)

    pq, pk = (-t_q) % bq, (-t_k) % bk
    padq = lambda x: jnp.pad(x, ((0, 0), (0, pq), (0, 0))) if pq else x  # noqa: E731
    padk = lambda x: jnp.pad(x, ((0, 0), (0, pk), (0, 0))) if pk else x  # noqa: E731
    qp, kp, vp, gp, deltap = padq(q), padk(k), padk(v), padq(g), padq(delta)
    # padded query rows get lse=+inf so their recomputed P is exactly zero
    lsep = (
        jnp.pad(lse, ((0, 0), (0, pq), (0, 0)), constant_values=jnp.inf)
        if pq
        else lse
    )
    nq, nk = qp.shape[1] // bq, kp.shape[1] // bk

    banded = _banded_ok(causal, window, shift, q_offset, t_q, t_k)
    if banded:
        grid_k = _banded_nj(nq, bq, bk, window)
        kvmap = lambda b, i, j: (  # noqa: E731
            b, jnp.clip(_banded_base(i, bq, bk, window) + j, 0, nk - 1), 0
        )
    else:
        grid_k = nk
        kvmap = lambda b, i, j: (b, j, 0)  # noqa: E731

    col_spec_q = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM)

    dq_kern = functools.partial(
        _dq_kernel, scale=scale, causal=causal, window=window, shift=shift,
        q_offset=q_offset,
        t_k=t_k, bq=bq, bk=bk, nk=grid_k, banded=banded, nk_real=nk,
    )
    dq = pl.pallas_call(
        dq_kern,
        grid=(bh, nq, grid_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), kvmap, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dv), kvmap, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, dv), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            col_spec_q,
            col_spec_q,
        ],
        out_specs=pl.BlockSpec(
            (1, bq, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=_sds((bh, nq * bq, d), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, gp, lsep, deltap)

    if banded:
        grid_q = _banded_q_nj(nk, bq, bk, window)
        qmap = lambda b, j, i: (  # noqa: E731
            b, jnp.clip((j * bk) // bq + i, 0, nq - 1), 0
        )
    else:
        grid_q = nq
        qmap = lambda b, j, i: (b, i, 0)  # noqa: E731

    col_spec_q_inner = pl.BlockSpec((1, bq, 1), qmap, memory_space=pltpu.VMEM)
    dkv_kern = functools.partial(
        _dkv_kernel, scale=scale, causal=causal, window=window, shift=shift,
        q_offset=q_offset,
        t_k=t_k, bq=bq, bk=bk, nq=grid_q, banded=banded, nq_real=nq,
    )
    dk, dv_ = pl.pallas_call(
        dkv_kern,
        grid=(bh, nk, grid_q),
        in_specs=[
            pl.BlockSpec((1, bq, d), qmap, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dv), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, dv), qmap, memory_space=pltpu.VMEM),
            col_spec_q_inner,
            col_spec_q_inner,
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dv), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _sds((bh, nk * bk, d), k.dtype, k),
            _sds((bh, nk * bk, dv), v.dtype, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, dv), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp, gp, lsep, deltap)
    return dq[:, :t_q, :], dk[:, :t_k, :], dv_[:, :t_k, :]


# ---------------------------------------------------------------------------
# custom_vjp wiring + public entry
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash_lse(q, k, v, scale, causal, window, shift, q_offset, bq, bk,
               interpret):
    return _flash_fwd_flat(
        q, k, v, scale, causal, window, bq, bk, interpret, shift=shift,
        q_offset=q_offset,
    )


def _flash_lse_vjp_fwd(q, k, v, scale, causal, window, shift, q_offset, bq,
                       bk, interpret):
    out, lse = _flash_fwd_flat(
        q, k, v, scale, causal, window, bq, bk, interpret, shift=shift,
        q_offset=q_offset,
    )
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_vjp_bwd(scale, causal, window, shift, q_offset, bq, bk,
                       interpret, res, gs):
    q, k, v, out, lse = res
    g, dlse = gs
    dq, dk, dv = _flash_bwd_flat(
        q, k, v, out, lse, g.astype(q.dtype), scale, causal, window, bq, bk,
        interpret, shift=shift, dlse=dlse, q_offset=q_offset,
    )
    return dq, dk, dv


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def _blocks(q, block_q, block_k, t_q, t_k):
    # clamp to the sequence length, then round up to the TPU sublane tile
    # (8 rows fp32, 16 bf16) — Mosaic may reject/deoptimize ragged blocks;
    # the existing tail padding + t_k masking absorbs the overshoot
    tile = 16 if q.dtype == jnp.bfloat16 else 8
    rup = lambda x: -(-x // tile) * tile  # noqa: E731
    return rup(min(block_q, max(t_q, 8))), rup(min(block_k, max(t_k, 8)))


def flash_attention(
    q: Array,
    k: Array,
    v: Array,
    *,
    causal: bool = True,
    window: Optional[int] = None,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
) -> Array:
    """Flash attention over [..., T, D] per-head tensors. Differentiable."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    batch_shape = q.shape[:-2]
    t_q, d = q.shape[-2:]
    t_k, dv = k.shape[-2], v.shape[-1]
    bh = 1
    for s in batch_shape:
        bh *= s
    bq, bk = _blocks(q, block_q, block_k, t_q, t_k)
    # one custom_vjp path serves both entries: the dropped lse output is
    # DCE'd by XLA and its zero cotangent costs one subtraction in the bwd
    out, _ = _flash_lse(
        q.reshape(bh, t_q, d),
        k.reshape(bh, t_k, d),
        v.reshape(bh, t_k, dv),
        float(scale), causal, window, 0, 0, bq, bk, interpret,
    )
    return out.reshape(*batch_shape, t_q, dv)


def flash_attention_lse(
    q: Array,
    k: Array,
    v: Array,
    *,
    causal: bool = True,
    window: Optional[int] = None,
    shift: int = 0,
    q_offset: int = 0,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
):
    """Flash attention that ALSO returns the row log-sum-exp
    ([..., T, 1] fp32) and is differentiable in both outputs — the block
    primitive for cross-shard online-softmax merges (parallel/ring.py):
    merging partial results needs lse, and the merged output's gradient
    flows through it (∂lse/∂S = P̂, folded into the backward's delta
    column). ``shift=1`` strengthens causal to the strict triangle."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    batch_shape = q.shape[:-2]
    t_q, d = q.shape[-2:]
    t_k, dv = k.shape[-2], v.shape[-1]
    bh = 1
    for s in batch_shape:
        bh *= s
    bq, bk = _blocks(q, block_q, block_k, t_q, t_k)
    out, lse = _flash_lse(
        q.reshape(bh, t_q, d),
        k.reshape(bh, t_k, d),
        v.reshape(bh, t_k, dv),
        float(scale), causal, window, shift, q_offset, bq, bk, interpret,
    )
    return (
        out.reshape(*batch_shape, t_q, dv),
        lse.reshape(*batch_shape, t_q, 1),
    )


__all__ = ["flash_attention", "flash_attention_lse"]
