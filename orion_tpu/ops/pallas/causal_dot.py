"""Pallas TPU kernel for the causal dot product (linear attention core).

TPU-native replacement for the reference's CUDA ``causal_dot_product`` /
kv-cumsum kernels (BASELINE.json north_star). Computes, per (batch·head):

    out[t]  = sum_{s<=t} (q_t . k_s) v_s  (+ q_t @ S0 for a carried-in state)
    S_final = S0 + sum_s k_s (x) v_s

Design (chunked kv-cumsum recurrence mapped onto the TPU):
- grid = (B*H, T/C) with the chunk axis innermost: TPU grids execute
  sequentially on a core, so a VMEM scratch accumulator carries the running
  [Dk, Dv] state S across chunk steps — the Pallas analogue of the CUDA
  kernel's shared-memory running state. S resets from S0 at chunk 0 of each
  (batch·head) program.
- per chunk, three MXU matmuls: scores = Q_c K_c^T (masked causally),
  intra = scores @ V_c, inter = Q_c @ S; then S += K_c^T V_c.
- all accumulation in fp32 regardless of input dtype (bf16 inputs hit the
  MXU natively with ``preferred_element_type=float32``).

The backward is two kernel passes (no time-flip copies):
    dq pass — the forward kernel on (g, v, k) with S0^T as carried state:
        dq[t] = sum_{s<=t} (g_t·v_s) k_s + g_t @ S0^T
    reverse pass (_bwd_rev_kernel) — grid walks chunks last->first with one
    carried state R_t = dSf^T + sum_{s>=t} g_s (x) q_s, emitting both
        dk[t] = v_t @ R_t   and   dv[t] = k_t @ R_t^T
    and dS0 = (final R)^T for free.
Wired up via jax.custom_vjp so the op is fully differentiable, including
through the carried state — which is what makes sequence-parallel training
(parallel/sequence.py) differentiable too.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array


def vma_zeros_state(kf: Array, vf: Array) -> Array:
    """[.., Dk, Dv] zeros *derived from k/v* (0 * k1^T v1) so the result
    inherits their varying-mesh-axes type: a plain jnp.zeros initial state
    trips shard_map(check_vma=True) bodies (carry/input unvarying while the
    data is varying). XLA folds the zero-multiply. One helper so the
    workaround has a single place to die when jnp.zeros grows a vma arg."""
    return 0.0 * jnp.einsum(
        "...td,...te->...de",
        kf[..., :1, :].astype(jnp.float32),
        vf[..., :1, :].astype(jnp.float32),
    )


def _sds(shape, dtype, like: Array):
    """ShapeDtypeStruct for a pallas_call output, inheriting ``like``'s
    varying-mesh-axes type so the kernels compose with
    shard_map(check_vma=True) bodies (sequence/pipeline parallel)."""
    try:
        vma = jax.api_util.shaped_abstractify(like).vma
    except Exception:
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _tri_mask(cdim: int, anti: bool = False):
    """Boolean (C, C) in-chunk time mask: causal ``s <= t`` rows>=cols, or
    anti-causal ``s >= t`` with ``anti=True``. One definition shared by all
    five chunk kernels so the numerator recurrences can't drift apart."""
    row = jax.lax.broadcasted_iota(jnp.int32, (cdim, cdim), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (cdim, cdim), 1)
    return row <= col if anti else row >= col


def _kernel(q_ref, k_ref, v_ref, s0_ref, out_ref, sf_ref, s_scr):
    c = pl.program_id(1)

    @pl.when(c == 0)
    def _():
        s_scr[:] = s0_ref[0].astype(jnp.float32)

    qi = q_ref[0]  # (C, Dk) input dtype
    ki = k_ref[0]
    vi = v_ref[0]

    scores = jax.lax.dot_general(
        qi,
        ki,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (C, C) fp32
    scores = jnp.where(_tri_mask(scores.shape[0]), scores, 0.0)

    intra = jnp.dot(scores, vi.astype(jnp.float32), preferred_element_type=jnp.float32)
    inter = jnp.dot(
        qi.astype(jnp.float32), s_scr[:], preferred_element_type=jnp.float32
    )
    out_ref[0] = (intra + inter).astype(out_ref.dtype)

    s_scr[:] = s_scr[:] + jax.lax.dot_general(
        ki,
        vi,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    sf_ref[0] = s_scr[:]


def _cdp_flat(
    q: Array, k: Array, v: Array, s0: Array, chunk: int, interpret: bool
) -> Tuple[Array, Array]:
    """Unnormalized causal dot product on flat [BH, T, D] inputs (T % chunk == 0)."""
    bh, t, dk = q.shape
    dv = v.shape[-1]
    nc = t // chunk

    grid = (bh, nc)
    out, sf = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, chunk, dk), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dk), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dv), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dk, dv), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, chunk, dv), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dk, dv), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _sds((bh, t, dv), q.dtype, q),
            _sds((bh, dk, dv), jnp.float32, q),
        ],
        scratch_shapes=[pltpu.VMEM((dk, dv), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * bh * t * (chunk * dk + chunk * dv + 2 * dk * dv),
            bytes_accessed=q.size * q.dtype.itemsize * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(q, k, v, s0)
    return out, sf


def _bwd_rev_core(
    q_ref, k_ref, v_ref, g_ref, gden_ref, rinit_ref, zr0_ref,
    dk_ref, dv_ref, rfin_ref, zrfin_ref, r_scr, zr_scr,
):
    """Reverse-walking fused backward body: one pass emits dk AND dv.

        dk[t] = v_t @ R_t,   dv[t] = k_t @ R_t^T,
        R_t   = dSf^T + sum_{s>=t} g_s (x) q_s   (Dv, Dk)

    The grid's chunk axis is index-mapped last->first, so the carried VMEM
    state R accumulates "later" chunks without materializing any time-flip
    (the previous formulation spent 3 kernel passes + 6 jnp.flip HBM copies;
    measured 0.64-0.79x vs XLA on-chip — this pass + the dq pass replace it).
    dS0 = (final R)^T falls out for free.

    With the denominator refs non-None (the normalized path), the dk part

        dk_den[t] = gzf + Σ_{s>=t} gden_s q_s

    rides as a second (1, Dk) suffix state over the same walk (zr0 = gzf,
    so the broadcast-to-every-t gzf term comes for free and the final
    state IS dz0 = gzf + Σ_t gden_t q_t). One body serves both kernels so
    the numerator recurrence cannot drift between the normalized and
    unnormalized backwards.
    """
    with_den = gden_ref is not None
    c = pl.program_id(1)

    @pl.when(c == 0)
    def _():
        r_scr[:] = rinit_ref[0].astype(jnp.float32)  # dSf^T
        if with_den:
            zr_scr[:] = zr0_ref[0].astype(jnp.float32)  # gzf (1, Dk)

    qi = q_ref[0]  # (C, Dk)
    ki = k_ref[0]
    vi = v_ref[0]
    gi = g_ref[0]  # (C, Dv)

    # within-chunk "s >= t" (anti-causal) contributions
    svg = jax.lax.dot_general(
        vi, gi, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (C, C): v_t · g_s
    anti = _tri_mask(svg.shape[0], anti=True)  # s >= t
    # jnp.where (not a float-mask multiply): a non-finite masked-out entry
    # must hard-zero, not turn into inf*0 = NaN — same style as _kernel
    svg = jnp.where(anti, svg, 0.0)
    skq = jnp.where(
        anti,
        jax.lax.dot_general(
            ki, qi, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ),
        0.0,
    )  # (C, C): k_t · q_s

    dk = (
        jnp.dot(svg, qi.astype(jnp.float32), preferred_element_type=jnp.float32)
        + jnp.dot(vi.astype(jnp.float32), r_scr[:], preferred_element_type=jnp.float32)
    )
    if with_den:
        gd = gden_ref[0].astype(jnp.float32)  # (C, 1)
        gq = gd * qi.astype(jnp.float32)  # (C, Dk)
        sufx = jnp.dot(
            anti.astype(jnp.float32), gq, preferred_element_type=jnp.float32
        )
        dk = dk + zr_scr[:] + sufx
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = (
        jnp.dot(skq, gi.astype(jnp.float32), preferred_element_type=jnp.float32)
        + jax.lax.dot_general(
            ki.astype(jnp.float32), r_scr[:],
            dimension_numbers=(((1,), (1,)), ((), ())),  # k_t @ R^T
            preferred_element_type=jnp.float32,
        )
    ).astype(dv_ref.dtype)

    r_scr[:] = r_scr[:] + jax.lax.dot_general(
        gi, qi, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # += sum_t g_t (x) q_t
    rfin_ref[0] = r_scr[:]
    if with_den:
        zr_scr[:] = zr_scr[:] + jnp.sum(gq, axis=0, keepdims=True)
        zrfin_ref[0] = zr_scr[:]


def _bwd_rev_kernel(q_ref, k_ref, v_ref, g_ref, rinit_ref, dk_ref, dv_ref, rfin_ref, r_scr):
    """Unnormalized-path arity adapter over ``_bwd_rev_core``."""
    _bwd_rev_core(
        q_ref, k_ref, v_ref, g_ref, None, rinit_ref, None,
        dk_ref, dv_ref, rfin_ref, None, r_scr, None,
    )


def _bwd_dq_den_kernel(
    g_ref, v_ref, k_ref, s0t_ref, gden_ref, z0_ref, dq_ref, s_scr, z_scr
):
    """Forward-walking fused dq for the NORMALIZED backward: the numerator
    part (same math as ``_kernel`` on (g, v, k) with S0^T carried in) plus
    the denominator part ``gden_t * (z0 + Σ_{s<=t} k_s)`` — the prefix-z
    state rides the same pass instead of a separate XLA cumsum over
    [BH, T, Dk] fp32 (measured: the two den cumsum passes were ~30% of
    fused-backward wall time at long T). In-chunk prefix sums are a
    lower-triangular matmul on the MXU."""
    c = pl.program_id(1)

    @pl.when(c == 0)
    def _():
        s_scr[:] = s0t_ref[0].astype(jnp.float32)  # (Dv, Dk)
        z_scr[:] = z0_ref[0].astype(jnp.float32)  # (1, Dk)

    gi = g_ref[0]  # (C, Dv)
    vi = v_ref[0]  # (C, Dv)
    ki = k_ref[0]  # (C, Dk)
    gd = gden_ref[0].astype(jnp.float32)  # (C, 1)

    scores = jax.lax.dot_general(
        gi, vi, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (C, C): g_t · v_s
    causal = _tri_mask(scores.shape[0]).astype(jnp.float32)  # s <= t
    scores = scores * causal

    kf = ki.astype(jnp.float32)
    intra = jnp.dot(scores, kf, preferred_element_type=jnp.float32)
    inter = jnp.dot(
        gi.astype(jnp.float32), s_scr[:], preferred_element_type=jnp.float32
    )
    kcum = jnp.dot(causal, kf, preferred_element_type=jnp.float32)  # prefix-incl
    dq_ref[0] = (intra + inter + gd * (z_scr[:] + kcum)).astype(dq_ref.dtype)

    s_scr[:] = s_scr[:] + jax.lax.dot_general(
        vi, ki, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # += Σ v_s (x) k_s
    z_scr[:] = z_scr[:] + jnp.sum(kf, axis=0, keepdims=True)


def _cdp_dq_den_flat(g, v, k, s0t, gden, z0, chunk, interpret):
    """dq (numerator + denominator parts) on flat inputs, emitted directly
    in ``g``'s dtype — nothing downstream adds to it."""
    bh, t, dk = k.shape
    dv = v.shape[-1]
    nc = t // chunk

    (dq,) = pl.pallas_call(
        _bwd_dq_den_kernel,
        grid=(bh, nc),
        in_specs=[
            pl.BlockSpec((1, chunk, dv), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dv), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dk), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dv, dk), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, 1), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, dk), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, chunk, dk), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[_sds((bh, t, dk), g.dtype, g)],
        scratch_shapes=[
            pltpu.VMEM((dv, dk), jnp.float32),
            pltpu.VMEM((1, dk), jnp.float32),
        ],
        interpret=interpret,
    )(g, v, k, s0t, gden, z0)
    return dq


# normalized path: _bwd_rev_core's full signature IS the kernel (all den
# refs live; dk/dv come out in the input dtype — they are final values)
_bwd_rev_den_kernel = _bwd_rev_core


def _cdp_rev_den_flat(q, k, v, g, gden, rinit, zr0, chunk, interpret):
    """Fused (dk, dv, ds0, dz0) for the normalized backward. dk/dv in the
    input dtypes (final values); ds0 [BH, Dk, Dv] and dz0 [BH, 1, Dk] fp32."""
    bh, t, dk = q.shape
    dv = v.shape[-1]
    nc = t // chunk
    rev = lambda b, c: (b, nc - 1 - c, 0)  # noqa: E731

    dk_out, dv_out, rfin, zrfin = pl.pallas_call(
        _bwd_rev_den_kernel,
        grid=(bh, nc),
        in_specs=[
            pl.BlockSpec((1, chunk, dk), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dk), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dv), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dv), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, 1), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dv, dk), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, dk), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, chunk, dk), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dv), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dv, dk), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, dk), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _sds((bh, t, dk), k.dtype, q),
            _sds((bh, t, dv), v.dtype, q),
            _sds((bh, dv, dk), jnp.float32, q),
            _sds((bh, 1, dk), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((dv, dk), jnp.float32),
            pltpu.VMEM((1, dk), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, g, gden, rinit, zr0)
    ds0 = jnp.swapaxes(rfin, -1, -2)
    return dk_out, dv_out, ds0, zrfin


def _cdp_rev_flat(q, k, v, g, rinit, chunk, interpret):
    """Fused (dk, dv, ds0) on flat [BH, T, D] inputs (T % chunk == 0).
    ``rinit`` = dSf^T [BH, Dv, Dk] fp32; returns ds0 [BH, Dk, Dv] fp32."""
    bh, t, dk = q.shape
    dv = v.shape[-1]
    nc = t // chunk
    rev = lambda b, c: (b, nc - 1 - c, 0)  # noqa: E731

    dk_out, dv_out, rfin = pl.pallas_call(
        _bwd_rev_kernel,
        grid=(bh, nc),
        in_specs=[
            pl.BlockSpec((1, chunk, dk), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dk), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dv), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dv), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dv, dk), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, chunk, dk), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dv), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dv, dk), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _sds((bh, t, dk), jnp.float32, q),
            _sds((bh, t, dv), jnp.float32, q),
            _sds((bh, dv, dk), jnp.float32, q),
        ],
        scratch_shapes=[pltpu.VMEM((dv, dk), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, rinit)
    ds0 = jnp.swapaxes(rfin, -1, -2)
    return dk_out, dv_out, ds0


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _cdp(q, k, v, s0, chunk, interpret):
    return _cdp_flat(q, k, v, s0, chunk, interpret)


def _cdp_fwd(q, k, v, s0, chunk, interpret):
    out, sf = _cdp_flat(q, k, v, s0, chunk, interpret)
    return (out, sf), (q, k, v, s0)


def _cdp_bwd(chunk, interpret, res, cts):
    q, k, v, s0 = res
    g, dsf = cts
    g = g.astype(q.dtype)
    # dq pass: same forward kernel on (g, v, k), with S0^T as its carried-in
    # state (out[t] = sum_{s<=t}(g_t.v_s) k_s + g_t @ S0^T)
    s0t = jnp.swapaxes(s0.astype(jnp.float32), -1, -2)
    dq, _ = _cdp_flat(g, v, k, s0t, chunk, interpret)
    # dk + dv + ds0: one reverse-walking fused pass, dSf^T seeding the state
    rinit = jnp.swapaxes(dsf.astype(jnp.float32), -1, -2)
    dk, dv, ds0 = _cdp_rev_flat(q, k, v, g, rinit, chunk, interpret)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), ds0


_cdp.defvjp(_cdp_fwd, _cdp_bwd)


def causal_dot_product_pallas(
    q: Array,
    k: Array,
    v: Array,
    *,
    chunk: Optional[int] = None,
    return_state: bool = False,
    initial_state: Optional[Array] = None,
    interpret: bool = False,
):
    """Public entry: arbitrary batch dims [..., T, Dk/Dv], auto pad/reshape.

    Differentiable (custom VJP), including through ``initial_state`` and the
    returned state. Zero-padding the tail chunk is safe: padded k/v rows
    contribute nothing to S, and padded outputs are sliced off.
    """
    batch_shape = q.shape[:-2]
    t, dk = q.shape[-2], q.shape[-1]
    dv = v.shape[-1]
    chunk = _auto_chunk(chunk, t)
    bh = 1
    for s in batch_shape:
        bh *= s

    qf = q.reshape(bh, t, dk)
    kf = k.reshape(bh, t, dk)
    vf = v.reshape(bh, t, dv)
    rem = (-t) % chunk
    if rem:
        pad = ((0, 0), (0, rem), (0, 0))
        qf, kf, vf = jnp.pad(qf, pad), jnp.pad(kf, pad), jnp.pad(vf, pad)

    if initial_state is None:
        s0 = vma_zeros_state(kf, vf)
    else:
        s0 = initial_state.astype(jnp.float32).reshape(bh, dk, dv)

    out, sf = _cdp(qf, kf, vf, s0, chunk, interpret)
    out = out[:, :t, :].reshape(*batch_shape, t, dv)
    if return_state:
        return out, sf.reshape(*batch_shape, dk, dv)
    return out


# ---------------------------------------------------------------------------
# Fused normalized linear attention: numerator, denominator, and both carried
# states (S, z) in ONE kernel pass — no separate fp32 cumsum over HBM for the
# normalizer (the reference fuses the same way inside its CUDA kernel pair:
# causal_dot_product + kv-cumsum; BASELINE.json north_star).
# ---------------------------------------------------------------------------


def _kernel_norm(
    q_ref, k_ref, v_ref, s0_ref, z0_ref,
    num_ref, den_ref, sf_ref, zf_ref,
    s_scr, z_scr,
):
    c = pl.program_id(1)

    @pl.when(c == 0)
    def _():
        s_scr[:] = s0_ref[0].astype(jnp.float32)
        z_scr[:] = z0_ref[0].astype(jnp.float32)

    qi = q_ref[0]  # (C, Dk)
    ki = k_ref[0]
    vi = v_ref[0]

    scores = jax.lax.dot_general(
        qi, ki,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    scores = jnp.where(_tri_mask(scores.shape[0]), scores, 0.0)

    intra = jnp.dot(scores, vi.astype(jnp.float32), preferred_element_type=jnp.float32)
    inter = jnp.dot(qi.astype(jnp.float32), s_scr[:], preferred_element_type=jnp.float32)
    num_ref[0] = intra + inter

    den_intra = jnp.sum(scores, axis=1, keepdims=True)  # (C, 1)
    den_inter = jax.lax.dot_general(
        qi.astype(jnp.float32), z_scr[:],  # same-dtype operands for Mosaic
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (C, 1)
    den_ref[0] = den_intra + den_inter

    s_scr[:] = s_scr[:] + jax.lax.dot_general(
        ki, vi,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    z_scr[:] = z_scr[:] + jnp.sum(
        ki.astype(jnp.float32), axis=0, keepdims=True
    )
    sf_ref[0] = s_scr[:]
    zf_ref[0] = z_scr[:]


def _cdpn_flat(q, k, v, s0, z0, chunk, interpret):
    """Fused pass on flat [BH, T, D] inputs (T % chunk == 0): returns
    (num fp32, den fp32 [BH,T,1], sf fp32, zf fp32 [BH,1,Dk])."""
    bh, t, dk = q.shape
    dv = v.shape[-1]
    nc = t // chunk

    num, den, sf, zf = pl.pallas_call(
        _kernel_norm,
        grid=(bh, nc),
        in_specs=[
            pl.BlockSpec((1, chunk, dk), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dk), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, dv), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dk, dv), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, dk), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, chunk, dv), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, 1), lambda b, c: (b, c, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, dk, dv), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, dk), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _sds((bh, t, dv), jnp.float32, q),
            _sds((bh, t, 1), jnp.float32, q),
            _sds((bh, dk, dv), jnp.float32, q),
            _sds((bh, 1, dk), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((dk, dv), jnp.float32),
            pltpu.VMEM((1, dk), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, s0, z0)
    return num, den, sf, zf


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _lin_attn_fused(q, k, v, s0, z0, chunk, eps, interpret):
    num, den, sf, zf = _cdpn_flat(q, k, v, s0, z0, chunk, interpret)
    out = (num / (den + eps)).astype(q.dtype)
    return out, sf, zf, den


def _lin_attn_fused_fwd(q, k, v, s0, z0, chunk, eps, interpret):
    num, den, sf, zf = _cdpn_flat(q, k, v, s0, z0, chunk, interpret)
    out = (num / (den + eps)).astype(q.dtype)
    return (out, sf, zf, den), (q, k, v, s0, z0, num, den)


def _fused_bwd_core(q, k, v, s0, z0, gnum, gden, gsf, gzf, chunk, interpret):
    """Shared backward for the fused pass given cotangents of the fp32
    numerator (gnum, already cast to q.dtype for the kernel), denominator
    (gden [BH,T,1] fp32), and final states (gsf, gzf).

    Two kernel passes, with the denominator backward FUSED into both (the
    earlier formulation ran it as two XLA cumsums over [BH,T,Dk] fp32 plus
    elementwise combines — pure HBM traffic):

    - forward walk (_bwd_dq_den_kernel): dq = numerator part + gden·zcum,
      the prefix-z carried in VMEM; emitted directly in q.dtype.
    - reverse walk (_bwd_rev_den_kernel): dk (incl. suffix Σ gden·q and
      the broadcast gzf, both riding a (1,Dk) carried state), dv, ds0;
      the final suffix state IS dz0.
    """
    gsf32 = gsf.astype(jnp.float32)
    gzf32 = gzf.astype(jnp.float32)
    gden32 = gden.astype(jnp.float32)

    s0t = jnp.swapaxes(s0.astype(jnp.float32), -1, -2)
    z032 = z0.astype(jnp.float32)
    dq = _cdp_dq_den_flat(gnum, v, k, s0t, gden32, z032, chunk, interpret)
    rinit = jnp.swapaxes(gsf32, -1, -2)
    dk, dv, ds0, dz0 = _cdp_rev_den_flat(
        q, k, v, gnum, gden32, rinit, gzf32, chunk, interpret
    )
    return dq.astype(q.dtype), dk, dv, ds0, dz0


def _lin_attn_fused_bwd(chunk, eps, interpret, res, cts):
    q, k, v, s0, z0, num, den = res
    gout, gsf, gzf, gden_ext = cts
    gout = gout.astype(jnp.float32)
    d = den + eps  # (BH, T, 1) fp32
    gnum = (gout / d).astype(q.dtype)
    gden = (
        -jnp.sum(gout * num, axis=-1, keepdims=True) / (d * d)
        + gden_ext.astype(jnp.float32)
    )  # (BH, T, 1)
    return _fused_bwd_core(q, k, v, s0, z0, gnum, gden, gsf, gzf, chunk, interpret)


_lin_attn_fused.defvjp(_lin_attn_fused_fwd, _lin_attn_fused_bwd)


# Raw (unnormalized) fused pass: hands back the fp32 numerator itself, so
# sequence parallelism can apply the cross-shard prefix correction without a
# bf16 round-trip through the normalized output (ADVICE r1).
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _lin_attn_fused_raw(q, k, v, s0, z0, chunk, interpret):
    return _cdpn_flat(q, k, v, s0, z0, chunk, interpret)


def _lin_attn_fused_raw_fwd(q, k, v, s0, z0, chunk, interpret):
    num, den, sf, zf = _cdpn_flat(q, k, v, s0, z0, chunk, interpret)
    return (num, den, sf, zf), (q, k, v, s0, z0)


def _lin_attn_fused_raw_bwd(chunk, interpret, res, cts):
    q, k, v, s0, z0 = res
    gnum32, gden, gsf, gzf = cts
    gnum = gnum32.astype(q.dtype)
    gden = gden.astype(jnp.float32)
    return _fused_bwd_core(q, k, v, s0, z0, gnum, gden, gsf, gzf, chunk, interpret)


_lin_attn_fused_raw.defvjp(_lin_attn_fused_raw_fwd, _lin_attn_fused_raw_bwd)


def _auto_chunk(chunk: Optional[int], t: int) -> int:
    from orion_tpu.ops.dispatch import resolve_chunk

    return resolve_chunk(chunk, t, "pallas")


def _prep_fused(q, k, v, chunk, initial_state):
    """Shared flatten + tail-pad + state-init for the fused entry points.
    Returns (qf, kf, vf, s0, z0, batch_shape, t, chunk) with chunk resolved
    to the tuned default when None."""
    chunk = _auto_chunk(chunk, q.shape[-2])
    batch_shape = q.shape[:-2]
    t, dk = q.shape[-2], q.shape[-1]
    dv = v.shape[-1]
    chunk = _auto_chunk(chunk, t)
    bh = 1
    for s in batch_shape:
        bh *= s

    qf = q.reshape(bh, t, dk)
    kf = k.reshape(bh, t, dk)
    vf = v.reshape(bh, t, dv)
    rem = (-t) % chunk
    if rem:
        pad = ((0, 0), (0, rem), (0, 0))
        qf, kf, vf = jnp.pad(qf, pad), jnp.pad(kf, pad), jnp.pad(vf, pad)

    if initial_state is None:
        s0 = vma_zeros_state(kf, vf)
        z0 = 0.0 * kf[:, :1].astype(jnp.float32)
    else:
        s0 = initial_state[0].astype(jnp.float32).reshape(bh, dk, dv)
        z0 = initial_state[1].astype(jnp.float32).reshape(bh, 1, dk)
    return qf, kf, vf, s0, z0, batch_shape, t, chunk


def linear_attention_pallas_fused(
    q: Array,
    k: Array,
    v: Array,
    *,
    chunk: Optional[int] = None,
    eps: float = 1e-6,
    initial_state: Optional[Tuple[Array, Array]] = None,
    return_state: bool = False,
    return_den: bool = False,
    interpret: bool = False,
):
    """Normalized causal linear attention, fully fused in one Pallas pass.

    ``return_den`` additionally returns the fp32 normalizer den[t] =
    q_t·(z0 + Σ_{s<=t} k_s) as [..., T] — what lets sequence parallelism
    correct a locally-normalized shard in O(T·D) after one kernel pass
    (parallel/sequence.py).

    out[t] = q_t·S_t / (q_t·z_t + eps) with S, z the kv-cumsum states;
    optionally seeded by ``initial_state=(S0 [..,Dk,Dv], z0 [..,Dk])`` and
    returning the final (S, z) — the prefill→decode handoff. Differentiable
    through everything including the states (custom VJP: two kernel passes,
    with the denominator backward fused in as carried (1, Dk) VMEM states —
    see ``_fused_bwd_core``)."""
    qf, kf, vf, s0, z0, batch_shape, t, chunk = _prep_fused(q, k, v, chunk, initial_state)
    dk, dv = q.shape[-1], v.shape[-1]

    out, sf, zf, den = _lin_attn_fused(qf, kf, vf, s0, z0, chunk, eps, interpret)
    out = out[:, :t, :].reshape(*batch_shape, t, dv)
    results = [out]
    if return_state:
        results.append(
            (sf.reshape(*batch_shape, dk, dv), zf.reshape(*batch_shape, dk))
        )
    if return_den:
        results.append(den[:, :t, 0].reshape(*batch_shape, t))
    return results[0] if len(results) == 1 else tuple(results)


def linear_attention_pallas_parts(
    q: Array,
    k: Array,
    v: Array,
    *,
    chunk: Optional[int] = None,
    initial_state: Optional[Tuple[Array, Array]] = None,
    interpret: bool = False,
):
    """One fused kernel pass, returning the raw fp32 parts:
    (num [..., T, Dv] fp32, den [..., T] fp32, (S [..,Dk,Dv], z [..,Dk])).

    The sequence-parallel path (parallel/sequence.py) consumes these: the
    exact fp32 numerator lets the cross-shard prefix correction avoid
    inheriting bf16 rounding from the locally-normalized output.
    Differentiable via custom VJP (same kernel identities, no quotient
    rule needed)."""
    qf, kf, vf, s0, z0, batch_shape, t, chunk = _prep_fused(q, k, v, chunk, initial_state)
    dk, dv = q.shape[-1], v.shape[-1]

    num, den, sf, zf = _lin_attn_fused_raw(qf, kf, vf, s0, z0, chunk, interpret)
    num = num[:, :t, :].reshape(*batch_shape, t, dv)
    den = den[:, :t, 0].reshape(*batch_shape, t)
    state = (sf.reshape(*batch_shape, dk, dv), zf.reshape(*batch_shape, dk))
    return num, den, state


__all__ = [
    "causal_dot_product_pallas",
    "linear_attention_pallas_fused",
    "linear_attention_pallas_parts",
]
