"""Kernel feature maps phi(.) for linear attention.

Linear attention replaces softmax(QK^T)V with phi(Q) (phi(K)^T V), where
phi maps head vectors to a non-negative feature space. The reference ships
these as CUDA "feature-map projection" kernels (BASELINE.json north_star);
on TPU they are cheap elementwise/VPU ops that XLA fuses into the
surrounding matmuls, so the XLA path is already optimal — only FAVOR+'s
random projection involves an MXU matmul.

Provided maps:
- ``elu1``   : x -> elu(x) + 1              (default; "Transformers are RNNs")
- ``relu``   : x -> max(x, 0)
- ``sqrelu`` : x -> max(x, 0)^2
- ``exp``    : x -> exp(x)                  (fp32; no data-dependent shift)
- ``favor``  : FAVOR+ positive random features approximating the softmax
               kernel (Performer), with an orthogonal random projection.
- ``identity``

``make_feature_map(name, ...)`` returns a ``FeatureMap`` whose ``__call__``
applies the map over the last axis. All maps are shape-preserving except
``favor`` (last dim -> ``num_features``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class FeatureMap:
    """A named feature map. ``fn`` maps [..., d] -> [..., d_out]."""

    name: str
    fn: Callable[[jax.Array], jax.Array]
    out_dim: Optional[int] = None  # None = same as input

    def __call__(self, x: jax.Array) -> jax.Array:
        return self.fn(x)


def _elu1(x):
    # elu(x) + 1 = exp(x) for x<0, x+1 for x>=0: strictly positive, smooth.
    return jax.nn.elu(x) + 1.0


def _relu(x):
    return jax.nn.relu(x)


def _sqrelu(x):
    r = jax.nn.relu(x)
    return r * r


def _exp(x):
    # Plain exp in fp32. No data-dependent stabilizer: phi must be a *fixed*
    # function — a per-vector or per-batch shift would rescale keys against
    # each other (biasing attention) and make prefill-phi differ from
    # decode-phi. exp of a normalized-head-vector coordinate is safely
    # within fp32 range.
    return jnp.exp(x.astype(jnp.float32)).astype(x.dtype)


def _orthogonal_gaussian(key: jax.Array, rows: int, cols: int) -> jax.Array:
    """Random matrix with orthogonal blocks of rows, Gaussian-normed rows.

    Standard FAVOR+ construction: stack of QR-orthogonalized Gaussian blocks,
    each row rescaled to the norm of a Gaussian vector, reducing estimator
    variance versus iid Gaussian projections.
    """
    n_blocks = -(-rows // cols)  # ceil
    keys = jax.random.split(key, n_blocks + 1)
    blocks = []
    for i in range(n_blocks):
        g = jax.random.normal(keys[i], (cols, cols), dtype=jnp.float32)
        q, _ = jnp.linalg.qr(g)
        blocks.append(q)
    w = jnp.concatenate(blocks, axis=0)[:rows]
    norms = jnp.sqrt(
        jnp.sum(
            jax.random.normal(keys[-1], (rows, cols), dtype=jnp.float32) ** 2,
            axis=-1,
            keepdims=True,
        )
    )
    return w * norms


def favor_features(
    key: jax.Array,
    dim: int,
    num_features: Optional[int] = None,
    stabilizer: float = 0.0,
) -> FeatureMap:
    """FAVOR+ positive random features for the softmax kernel (Performer).

    phi(x) = exp(w_i . x / d^(1/4)... ) — concretely, with x' = x / d^(1/4):
        phi(x)_i = exp(w_i . x' - |x'|^2 / 2 - c) / sqrt(m)
    where c stabilizes the exponent. E[phi(q).phi(k)] = exp(q.k / sqrt(d)),
    the softmax kernel without normalization.
    """
    m = num_features or dim
    w = _orthogonal_gaussian(key, m, dim)  # [m, d]

    def fn(x):
        xf = x.astype(jnp.float32) / (dim**0.25)
        proj = jnp.einsum("...d,md->...m", xf, w)
        sq = 0.5 * jnp.sum(xf * xf, axis=-1, keepdims=True)
        # ``stabilizer`` is a FIXED constant (default 0), not data-dependent:
        # phi must be the same function at prefill and decode time, and a
        # per-key rescale would reweight keys against each other and bias
        # the attention estimate. The exponent proj - sq is bounded above by
        # |w_i|^2/2 ~ d/2, within fp32 range for practical head dims; pass a
        # positive ``stabilizer`` if working far outside that regime.
        return (jnp.exp(proj - sq - stabilizer) / jnp.sqrt(m)).astype(x.dtype)

    return FeatureMap(name="favor", fn=fn, out_dim=m)


_SIMPLE = {
    "elu1": _elu1,
    "relu": _relu,
    "sqrelu": _sqrelu,
    "exp": _exp,
    "identity": lambda x: x,
}
_BUILTIN = frozenset(_SIMPLE)  # protected from re-registration; user names aren't


def register_feature_map(name: str, fn=None):
    """Register a custom elementwise feature map under ``name`` so any
    config can select it (``ModelConfig(feature_map=name)``) — the
    user-extensibility hook the reference exposes through its attention/
    feature-map registry (BASELINE.json names the feature-map projections
    as a pluggable kernel family; reference checkout never mounted —
    SURVEY.md §0). Usable directly or as a decorator:

        @register_feature_map("softplus")
        def _softplus(x):
            return jax.nn.softplus(x)

    The map must be positive-valued for causal linear attention (the
    normalizer q·z must stay > 0) and elementwise over the feature dim.
    Re-registering a BUILT-IN name raises; re-registering your own custom
    name overwrites it (notebook/REPL iteration).
    """

    def install(f):
        # "favor" and "learnable" are special-cased inside the Attention
        # module (random features / learned projection) — registering them
        # here would be silently shadowed there, so reserve the names too
        if name in _BUILTIN or name in ("favor", "learnable"):
            raise ValueError(f"feature map {name!r} is built-in; pick a new name")
        _SIMPLE[name] = f
        return f

    return install if fn is None else install(fn)


def make_feature_map(
    name: str,
    *,
    key: Optional[jax.Array] = None,
    dim: Optional[int] = None,
    num_features: Optional[int] = None,
) -> FeatureMap:
    """Build a feature map by name (built-in or registered via
    ``register_feature_map``). ``favor`` requires ``key`` and ``dim``."""
    if name == "favor":
        if key is None or dim is None:
            raise ValueError("favor feature map requires key= and dim=")
        return favor_features(key, dim, num_features)
    if name not in _SIMPLE:
        raise ValueError(f"unknown feature map {name!r}; have {sorted(_SIMPLE)} + ['favor']")
    return FeatureMap(name=name, fn=_SIMPLE[name])


__all__ = [
    "FeatureMap",
    "make_feature_map",
    "register_feature_map",
    "favor_features",
]
