"""Backend dispatch for the hot ops: backend="xla" | "pallas" | "auto".

Mirrors the reference's CUDA-vs-CPU dispatch for ``causal_dot_product``
(BASELINE.json north_star asks for the Pallas path to be "emitted through a
backend='xla' dispatch"). "auto" picks Pallas on TPU and the pure-XLA
chunked scan elsewhere (CPU/GPU and unit tests). The Pallas kernel can also
run anywhere via interpret mode (used by the parity tests).
"""

from __future__ import annotations

from typing import Optional

import jax

_VALID = ("auto", "xla", "pallas", "pallas_interpret", "eager")


def _pallas_available() -> bool:
    try:
        from orion_tpu.ops.pallas import causal_dot  # noqa: F401

        return True
    except ImportError:
        return False


def default_backend() -> str:
    try:
        plat = jax.devices()[0].platform
    except RuntimeError:
        plat = "cpu"
    return "pallas" if plat == "tpu" and _pallas_available() else "xla"


def resolve(backend: str) -> str:
    if backend not in _VALID:
        raise ValueError(f"backend must be one of {_VALID}, got {backend!r}")
    return default_backend() if backend == "auto" else backend


def resolve_chunk(chunk: Optional[int], t: int, backend: str) -> int:
    """Tuned default chunk for the causal linear-attention kernels.

    On-chip sweep (BENCH r2, v5e): the Pallas kernel is fastest at C=512
    for every T from 2k to 32k (grid overhead amortized, 512-wide MXU
    matmuls; C=1024 regresses); the XLA scan's sweet spot stays C=128.
    Short sequences fall back to one sublane-aligned chunk."""
    if chunk is not None:
        return chunk
    if backend.startswith("pallas"):
        return min(512, max(8, -(-t // 8) * 8))
    return 128


def causal_dot_product(
    q,
    k,
    v,
    *,
    backend: str = "auto",
    chunk: Optional[int] = None,
    return_state: bool = False,
    initial_state=None,
):
    """Dispatch ``out[t] = sum_{s<=t}(q_t.k_s) v_s`` to the chosen backend.

    ``return_state`` additionally returns the final S = sum k_s ⊗ v_s (fp32).
    """
    # NB: `from orion_tpu.ops import linear_attention` would resolve to the
    # *function* re-exported by ops/__init__, which shadows the submodule of
    # the same name — import the callables by full dotted path instead.
    from orion_tpu.ops.linear_attention import (
        causal_dot_product_chunked,
        causal_dot_product_eager,
    )

    b = resolve(backend)
    chunk = resolve_chunk(chunk, q.shape[-2], b)
    if b == "eager":
        import jax.numpy as jnp

        out = causal_dot_product_eager(q, k, v)
        if initial_state is not None:
            inter = jnp.einsum(
                "...td,...de->...te",
                q.astype(jnp.float32),
                initial_state.astype(jnp.float32),
            )
            out = (out.astype(jnp.float32) + inter).astype(q.dtype)
        if return_state:
            s = jnp.einsum(
                "...td,...te->...de", k.astype(jnp.float32), v.astype(jnp.float32)
            )
            if initial_state is not None:
                s = s + initial_state.astype(jnp.float32)
            return out, s
        return out
    if b in ("pallas", "pallas_interpret"):
        from orion_tpu.ops.pallas import causal_dot as pcd

        return pcd.causal_dot_product_pallas(
            q,
            k,
            v,
            chunk=chunk,
            return_state=return_state,
            initial_state=initial_state,
            interpret=(b == "pallas_interpret"),
        )
    return causal_dot_product_chunked(
        q, k, v, chunk=chunk, return_state=return_state, initial_state=initial_state
    )


__all__ = ["causal_dot_product", "default_backend", "resolve", "resolve_chunk"]
