"""`python -m orion_tpu.evaluate` — held-out perplexity evaluation
(SURVEY.md T7).

Loads a training checkpoint and reports loss/perplexity over N batches of a
token-bin dataset (or the synthetic stream). Library: ``evaluate_lm(...)``.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional

import jax
import jax.numpy as jnp
import optax

from orion_tpu.models.configs import get_config
from orion_tpu.models.transformer import TransformerLM
from orion_tpu.training.data import make_dataset


def lm_eval_sums(model: TransformerLM, params, batch, logits_fn=None):
    """batch [B, T+1] -> (sum of next-token xent, token count). The single
    eval-loss definition — Trainer._eval_step delegates here too, so the
    periodic in-training eval and this CLI can never drift apart.
    ``logits_fn(model, params, x)`` overrides the forward (the pp Trainer
    passes the pipelined one); default is the fused-CE chunked forward
    (ops/fused_ce.py — same numbers, no [B, T, V] fp32 logits, so eval
    fits wherever training does, e.g. T=32k on one chip)."""
    x, y = batch[:, :-1], batch[:, 1:]
    if logits_fn is None:
        from orion_tpu.ops.fused_ce import fused_ce_ok, model_token_losses

        if fused_ce_ok(model):
            losses, _ = model_token_losses(model, params, x, y)
            return losses.sum(), jnp.asarray(losses.size, jnp.float32)
        logits = model.apply(params, x)
    else:
        logits = logits_fn(model, params, x)
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    return losses.sum(), jnp.asarray(losses.size, jnp.float32)


def evaluate_lm(
    model: TransformerLM,
    params,
    dataset,
    batch_size: int = 8,
    n_batches: int = 16,
    seed: int = 123,
) -> dict:
    @jax.jit
    def eval_step(params, batch):
        return lm_eval_sums(model, params, batch)

    total, count = 0.0, 0.0
    for i in range(n_batches):
        batch = jnp.asarray(dataset.batch(seed, i, batch_size))
        s, c = eval_step(params, batch)
        total += float(s)
        count += float(c)
    loss = total / max(count, 1.0)
    return {
        "eval_loss": loss,
        "eval_ppl": float(jnp.exp(jnp.minimum(loss, 20.0))),
        "tokens": int(count),
    }


def main(argv=None) -> int:
    from orion_tpu.utils.cache import enable_compile_cache

    enable_compile_cache()
    p = argparse.ArgumentParser("orion_tpu.evaluate")
    p.add_argument("--config", default="tiny")
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--step", type=int, default=None)
    p.add_argument("--data", default="synthetic")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--n-batches", type=int, default=16)
    p.add_argument("--quant", default="", choices=["", "int8", "int4"],
                   help="evaluate through the weight-streamed decode model "
                        "(the quant acceptance bar: eval-ppl delta vs fp32 "
                        "on the same held-out data)")
    p.add_argument("--ckpt-attempts", type=int, default=4,
                   help="total tries for the checkpoint load (transient "
                        "I/O retried with jittered backoff; 1 = no retry)")
    args = p.parse_args(argv)

    from orion_tpu.generate import load_params
    from orion_tpu.resilience.retry import RetryPolicy

    cfg = get_config(args.config)
    # hardened serving-side loader (generate.load_params): retried I/O,
    # manifest-verified params, and — when --step is NOT pinned — fallback
    # to the newest intact step, so a torn latest checkpoint degrades the
    # eval to slightly-stale params instead of killing it
    params, step = load_params(
        args.ckpt_dir, args.step,
        retry=RetryPolicy(attempts=max(args.ckpt_attempts, 1)),
    )
    from orion_tpu.generate import adapt_config_to_params, unstack_if_pipeline

    cfg = adapt_config_to_params(cfg, params)
    assert args.seq_len < cfg.max_seq_len, (
        f"--seq-len {args.seq_len} needs positions up to {args.seq_len}, but "
        f"the checkpoint was trained with max_seq_len={cfg.max_seq_len}"
    )
    model = TransformerLM(cfg)
    params, _ = unstack_if_pipeline(model, params)
    if args.quant:
        from orion_tpu.generate import quantize_for_decode

        model, params = quantize_for_decode(model, params, mode=args.quant)
    dataset = make_dataset(args.data, args.seq_len, cfg.vocab_size)
    res = evaluate_lm(model, params, dataset, args.batch_size, args.n_batches)
    res["step"] = step
    if args.quant:
        res["quant"] = args.quant
    print(res)
    return 0


if __name__ == "__main__":
    sys.exit(main())
