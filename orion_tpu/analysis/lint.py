"""Tier A: AST lint engine for orion-specific JAX hazards.

Rules live in ``analysis/rules/`` and are pure AST checks — no imports of the
code under analysis, so a lint pass can never crash on (or be slowed by) the
modules it audits. Each rule gets a :class:`ModuleContext` with the parsed
tree plus the two pieces of derived information most rules share:

- **traced scopes** — the function defs that jax will trace: functions
  decorated with ``@jax.jit`` / ``@partial(jax.jit, ...)``, functions passed
  by name into ``jax.jit(...)`` / ``jax.lax.scan`` / grad / vmap / shard_map
  etc., everything lexically nested inside those, and (fixpoint) every
  same-module function they call by name. Host-side code like CLI mains
  never enters the set, so host-only idioms (``float(metrics["loss"])``)
  don't false-positive.
- **line suppression** — ``# orion: noqa[rule-id]`` (or several ids,
  comma-separated) on the finding's line suppresses it; a bare
  ``# orion: noqa`` suppresses every rule on that line. Suppression works
  on LOGICAL lines: a statement spanning several physical lines (tokenized
  the way the compiler does) is suppressed by a noqa on any of them, so a
  finding reported against a multi-line call's first line is covered by a
  trailing comment after the closing paren and vice versa.

``lint_source`` checks one in-memory module (what the unit tests use);
``lint_paths`` walks files and applies the baseline.
"""

from __future__ import annotations

import ast
import os
import re
from functools import cached_property
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set

from orion_tpu.analysis.findings import (
    BaselineEntry,
    Finding,
    apply_baseline,
    normalize_path,
)

NOQA_RE = re.compile(r"#\s*orion:\s*noqa(?:\[([A-Za-z0-9_\-,\s]+)\])?")
NOQA_ALL = frozenset({"*"})

# Call targets whose function-valued arguments jax traces.
_TRACE_WRAPPERS = {
    "jax.jit", "jit", "pjit", "jax.pjit",
    "jax.grad", "jax.value_and_grad", "grad", "value_and_grad",
    "jax.vmap", "jax.pmap", "vmap", "pmap",
    "jax.checkpoint", "jax.remat", "checkpoint", "remat",
    "jax.lax.scan", "lax.scan", "scan",
    "jax.lax.while_loop", "lax.while_loop", "while_loop",
    "jax.lax.fori_loop", "lax.fori_loop", "fori_loop",
    "jax.lax.cond", "lax.cond", "jax.lax.switch", "lax.switch",
    "jax.lax.associative_scan", "lax.associative_scan",
    "shard_map", "jax.shard_map", "shard_map_bh",
    "jax.eval_shape", "jax.make_jaxpr",
    "jax.custom_vjp", "jax.custom_jvp",
}

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit", "nn.jit"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.debug.print``-style dotted name for Name/Attribute chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    """Does this decorator/callee expression denote jax.jit (possibly via
    ``partial(jax.jit, ...)`` or a configured ``jax.jit(...)`` call)?"""
    name = dotted_name(node)
    if name in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in _JIT_NAMES:
            return True  # @jax.jit(static_argnums=...)
        if fname in ("partial", "functools.partial") and node.args:
            return _is_jit_expr(node.args[0])
    return False


def jit_decorations(fn: ast.AST) -> List[ast.expr]:
    return [d for d in getattr(fn, "decorator_list", []) if _is_jit_expr(d)]


class ModuleContext:
    """One parsed module plus the derived info rules share."""

    def __init__(self, source: str, path: str = "<memory>", root: str = ""):
        self.source = source
        self.path = (
            normalize_path(path, root) if path != "<memory>" else path
        )
        self.tree = ast.parse(source)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._orion_parent = parent  # type: ignore[attr-defined]

    # -- path predicates -----------------------------------------------------

    @property
    def is_test(self) -> bool:
        base = self.path.rsplit("/", 1)[-1]
        return "tests/" in self.path or base.startswith("test_")

    @property
    def is_hot_path(self) -> bool:
        """Modules where a Python-loop jnp accumulation is a perf bug, not
        a style nit: the trainer, the decode path, and every op."""
        p = self.path
        return any(
            s in p
            for s in (
                "training/trainer", "generate", "/ops/", "train_lra",
                "serving/",
            )
        ) or p.startswith("ops/")

    @property
    def is_fleet(self) -> bool:
        """The replicated-serving layer (orion_tpu/fleet/): every
        cross-process wait — control-channel reads, child joins, event
        waits — must carry a timeout, because the peer is a separate OS
        process that can die or wedge at any time (the unbounded-wait
        rule widens its method set here)."""
        return "fleet/" in self.path

    @property
    def is_obs(self) -> bool:
        """The telemetry spine (orion_tpu/obs/): scrape handlers run on
        daemon HTTP threads against locks the scheduler also holds — a
        scrape read that blocks unboundedly on a lock or queue turns a
        wedged scheduler into a wedged endpoint (and vice versa), so the
        unbounded-wait rule widens its method set here too (including
        bare ``.acquire()``)."""
        return "orion_tpu/obs/" in self.path or self.path.startswith("obs/")

    @property
    def is_pallas_module(self) -> bool:
        return "ops/pallas/" in self.path and not self.path.endswith(
            "__init__.py"
        )

    # -- traced-scope analysis ----------------------------------------------

    @cached_property
    def function_defs(self) -> List[ast.AST]:
        return [
            n
            for n in ast.walk(self.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]

    @cached_property
    def traced_functions(self) -> Set[ast.AST]:
        """Function defs jax will trace (see module docstring)."""
        by_name: Dict[str, List[ast.AST]] = {}
        for fn in self.function_defs:
            by_name.setdefault(fn.name, []).append(fn)

        roots: Set[ast.AST] = set()
        for fn in self.function_defs:
            if jit_decorations(fn) or any(
                _is_trace_decorator(d) for d in fn.decorator_list
            ):
                roots.add(fn)

        # functions passed by name (or as self.method) into a tracing call
        referenced: Set[str] = set()
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            callee = dotted_name(call.func)
            if callee not in _TRACE_WRAPPERS:
                continue
            for arg in list(call.args) + [k.value for k in call.keywords]:
                name = dotted_name(arg)
                if name:
                    referenced.add(name.rsplit(".", 1)[-1])
        for name in referenced:
            roots.update(by_name.get(name, []))

        # close over lexical nesting and same-module direct calls
        traced: Set[ast.AST] = set()
        frontier = list(roots)
        while frontier:
            fn = frontier.pop()
            if fn in traced:
                continue
            traced.add(fn)
            for node in ast.walk(fn):
                if (
                    isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node is not fn
                ):
                    frontier.append(node)
                elif isinstance(node, ast.Call):
                    callee = dotted_name(node.func)
                    if callee:
                        frontier.extend(
                            by_name.get(callee.rsplit(".", 1)[-1], [])
                        )
        return traced

    def in_traced_scope(self, node: ast.AST) -> bool:
        cur = getattr(node, "_orion_parent", None)
        while cur is not None:
            if cur in self.traced_functions:
                return True
            cur = getattr(cur, "_orion_parent", None)
        return False

    # -- suppression ---------------------------------------------------------

    @cached_property
    def noqa_lines(self) -> Dict[int, FrozenSet[str]]:
        out: Dict[int, FrozenSet[str]] = {}
        for i, line in enumerate(self.source.splitlines(), start=1):
            m = NOQA_RE.search(line)
            if not m:
                continue
            ids = m.group(1)
            out[i] = (
                frozenset(s.strip() for s in ids.split(",") if s.strip())
                if ids
                else NOQA_ALL
            )
        return out

    @cached_property
    def logical_lines(self) -> Dict[int, range]:
        """physical line -> the physical-line range of its logical line.

        Logical lines come from the tokenizer (a NEWLINE token ends one;
        NL/COMMENT inside brackets do not), so a multi-line call or def
        header is ONE suppression unit while a function body is not —
        a bare noqa on a ``def`` line never mutes the whole function."""
        import io
        import tokenize

        out: Dict[int, range] = {}
        start: Optional[int] = None
        skip = (
            tokenize.NL, tokenize.COMMENT, tokenize.INDENT,
            tokenize.DEDENT, tokenize.ENDMARKER,
        )
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline
            )
            for tok in tokens:
                if tok.type == tokenize.NEWLINE:
                    if start is not None:
                        span = range(start, tok.end[0] + 1)
                        for ln in span:
                            out[ln] = span
                    start = None
                elif tok.type not in skip and start is None:
                    start = tok.start[0]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return {}  # unparseable tail: fall back to physical-line noqa
        return out

    def suppressed(self, finding: Finding) -> bool:
        for line in self.logical_lines.get(finding.line, (finding.line,)):
            ids = self.noqa_lines.get(line)
            if ids is not None and (ids is NOQA_ALL or finding.rule in ids):
                return True
        return False


def _is_trace_decorator(node: ast.AST) -> bool:
    name = dotted_name(node if not isinstance(node, ast.Call) else node.func)
    return name in _TRACE_WRAPPERS


# -- engine -------------------------------------------------------------------


def default_rules():
    from orion_tpu.analysis.rules import ALL_RULES

    return list(ALL_RULES.values())


def lint_source(
    source: str,
    path: str = "<memory>",
    rules=None,
    root: str = "",
    keep_suppressed: bool = False,
) -> List[Finding]:
    """Lint one module's source; returns unsuppressed findings, sorted.
    ``keep_suppressed`` keeps noqa'd findings with ``status="suppressed"``
    (the --format json path) instead of dropping them."""
    import dataclasses

    ctx = ModuleContext(source, path, root)
    findings: List[Finding] = []
    for rule in rules if rules is not None else default_rules():
        findings.extend(rule.check(ctx))
    if keep_suppressed:
        findings = [
            dataclasses.replace(f, status="suppressed")
            if ctx.suppressed(f) else f
            for f in findings
        ]
    else:
        findings = [f for f in findings if not ctx.suppressed(f)]
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames if d != "__pycache__"
                )
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        yield os.path.join(dirpath, f)


def lint_paths(
    paths: Sequence[str],
    rules=None,
    baseline: Sequence[BaselineEntry] = (),
    root: str = "",
    keep_suppressed: bool = False,
) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            findings.extend(lint_source(
                source, path, rules=rules, root=root,
                keep_suppressed=keep_suppressed,
            ))
        except SyntaxError as e:
            # the engine must never crash on the code under audit — an
            # unparseable file is itself a (non-suppressable) finding
            findings.append(Finding(
                "parse-error", normalize_path(path, root), e.lineno or 0,
                f"file does not parse: {e.msg}",
            ))
    if keep_suppressed:
        from orion_tpu.analysis.findings import annotate_baseline

        return annotate_baseline(findings, baseline)
    return apply_baseline(findings, baseline)


__all__ = [
    "ModuleContext", "dotted_name", "jit_decorations", "lint_source",
    "lint_paths", "iter_py_files", "default_rules",
]
