"""Tier E: closed compile-universe audit (ISSUE 18).

``python -m orion_tpu.analysis --tier programs`` — a pure-AST +
lowering-only (never-execute) auditor of the jit program universe against
the declaration in ``analysis/programs.py``, the way Tier D audits the
threaded stack against ``serving/locks.py``. ROADMAP item 1's executable
store assumes the universe is closed: every entrypoint registered, every
static key space finite, the AOT plan exactly what a replica compiles.
Tier E turns each of those assumptions into a findings-producing rule:

- **unregistered-jit** — a ``jax.jit``/``pjit``/``shard_map`` site in
  ``generate.py``/``serving/``/``parallel/`` with no
  :class:`~orion_tpu.analysis.programs.ProgramDecl` row. A new jit is a
  new executable the fleet must plan for; declaring it is the act of
  planning.
- **unbounded-static-key** — a static parameter of a registered program
  (decl ``keyspace="closed"``) whose value, traced interprocedurally
  through same-module call sites, derives from request/runtime data
  rather than a declared finite domain (``programs.FINITE_DOMAINS`` /
  config-attribute reads / literals). Also fires when the AST static
  signature drifts from the declared ``static_args``.
- **recompile-hazard** — silent cache-blowup shapes: a jitted function
  closing over a module/enclosing-scope array, dict/set iteration or a
  float literal feeding a static argument, ``functools.partial``
  re-wrapping a registered wrapper inside a function body.
- **plan-drift** — ``generate.DECODE_PROGRAMS`` diffed against the
  declared decode section, and ``aot.decode_plan``'s inventory diffed
  against :func:`programs.expected_decode_universe` per declared check
  footprint; the canonical footprint is additionally LOWERED (memoized
  process-wide) so a planned program that no longer lowers is a finding,
  not a cold-replica surprise.
- **donation-drift** — ``donate_argnums`` on registered wrappers checked
  three-way: AST vs declaration vs the golden snapshots' recorded
  donation counts.

Findings ride the standard pipeline: ``# orion: noqa[rule-id]``,
baseline.json with rationale, ``--format json`` statuses. Like Tier D,
the rules deliberately do NOT register in ``rules/__init__.ALL_RULES``:
they run only over the Tier E packages and carry their own fixture
contract in ``tests/test_program_audit.py``.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Any, Dict, Iterator, List, Optional, Tuple

from orion_tpu.analysis import programs as _decls
from orion_tpu.analysis.findings import (
    BaselineEntry,
    Finding,
    annotate_baseline,
    apply_baseline,
    normalize_path,
)
from orion_tpu.analysis.lint import (
    ModuleContext,
    _is_jit_expr,
    dotted_name,
    jit_decorations,
    lint_paths,
)

RULE_UNREGISTERED = "unregistered-jit"
RULE_UNBOUNDED = "unbounded-static-key"
RULE_HAZARD = "recompile-hazard"
RULE_PLAN = "plan-drift"
RULE_DONATION = "donation-drift"

ALL_PROGRAM_CHECKS = (
    RULE_UNREGISTERED, RULE_UNBOUNDED, RULE_HAZARD, RULE_PLAN,
    RULE_DONATION,
)

# Tier E scope (ISSUE 18): everything that creates device programs
TIER_E_PATHS = (
    "orion_tpu/generate.py", "orion_tpu/serving", "orion_tpu/parallel",
)

_SHARD_NAMES = frozenset({"shard_map", "jax.shard_map"})

_FINITE_BUILTINS = frozenset({
    "int", "bool", "str", "len", "min", "max", "abs", "round", "tuple",
    "sorted",
})

_ARRAY_ROOTS = ("jnp.", "np.", "numpy.", "jax.numpy.", "jax.random.")


class ProgramTable:
    """The declaration, indexed for the rules (injectable in tests)."""

    def __init__(self, decls, finite_domains=None, finite_attr_bases=None):
        self.decls: Tuple[Any, ...] = tuple(decls)
        self.by_site: Dict[Tuple[str, str], Any] = {
            (d.module, d.qualname): d for d in self.decls
        }
        self.by_name: Dict[str, Any] = {d.name: d for d in self.decls}
        self.finite_domains: Dict[str, str] = dict(
            _decls.FINITE_DOMAINS if finite_domains is None
            else finite_domains
        )
        self.finite_attr_bases = frozenset(
            _decls.FINITE_ATTR_BASES if finite_attr_bases is None
            else finite_attr_bases
        )
        self.qualnames = frozenset(d.qualname for d in self.decls)

    def decl_at(self, path: str, qualname: str):
        return self.by_site.get((path, qualname))

    def section(self, name: str):
        return [d for d in self.decls if d.section == name]


_TABLE: Optional[ProgramTable] = None


def load_program_table() -> ProgramTable:
    global _TABLE
    if _TABLE is None:
        _TABLE = ProgramTable(_decls.PROGRAMS)
    return _TABLE


# -- the per-module model ------------------------------------------------------


class _FnScope:
    __slots__ = ("node", "params")

    def __init__(self, node: ast.AST):
        self.node = node
        a = node.args
        self.params = [p.arg for p in a.posonlyargs + a.args]


class ProgramModel:
    """Jit sites, call sites, and value classification for one module."""

    def __init__(self, ctx: ModuleContext, table: ProgramTable):
        self.ctx = ctx
        self.table = table
        tree = ctx.tree
        self.defs = [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        self.fns_by_name: Dict[str, List[_FnScope]] = {}
        for fn in self.defs:
            self.fns_by_name.setdefault(fn.name, []).append(_FnScope(fn))
        dec_nodes = set()
        for fn in self.defs:
            for d in fn.decorator_list:
                for sub in ast.walk(d):
                    dec_nodes.add(id(sub))
        # decorated jit wrappers: (def node, the jit decorator expr)
        self.jit_defs: List[Tuple[ast.AST, ast.expr]] = [
            (fn, jit_decorations(fn)[0])
            for fn in self.defs
            if jit_decorations(fn)
        ]
        # bare jit/shard_map creation sites outside decorator expressions
        self.bare_sites: List[Tuple[ast.Call, str]] = []
        for call in ast.walk(tree):
            if not isinstance(call, ast.Call) or id(call) in dec_nodes:
                continue
            fname = dotted_name(call.func)
            if _is_jit_expr(call.func) or fname in _SHARD_NAMES:
                self.bare_sites.append((call, self._site_qualname(call)))
        # call sites by callee name (plain Name calls only)
        self.calls_by_name: Dict[str, List[ast.Call]] = {}
        for call in ast.walk(tree):
            if isinstance(call, ast.Call) and isinstance(
                call.func, ast.Name
            ):
                self.calls_by_name.setdefault(
                    call.func.id, []
                ).append(call)
        # module-level assignments: name -> RHS expr
        self.module_assigns: Dict[str, ast.expr] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                self.module_assigns[node.targets[0].id] = node.value

    # -- structure helpers ----------------------------------------------------

    def enclosing_fn(self, node: ast.AST) -> Optional[ast.AST]:
        cur = getattr(node, "_orion_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = getattr(cur, "_orion_parent", None)
        return None

    def _site_qualname(self, call: ast.Call) -> str:
        fn = self.enclosing_fn(call)
        if fn is not None:
            return fn.name
        # module-level site: use the assignment target when there is one
        cur = getattr(call, "_orion_parent", None)
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = getattr(cur, "_orion_parent", None)
        if isinstance(cur, ast.Assign) and len(cur.targets) == 1 and \
                isinstance(cur.targets[0], ast.Name):
            return cur.targets[0].id
        return "<module>"

    def static_params(
        self, fn: ast.AST, dec: ast.expr
    ) -> List[Tuple[Optional[int], str]]:
        """(position, param name) for each static argument the decorator
        declares, in declaration order. Unresolvable specs are skipped —
        the signature-drift check surfaces them via name mismatch."""
        kws: Dict[str, ast.expr] = {}
        if isinstance(dec, ast.Call):
            kws = {k.arg: k.value for k in dec.keywords if k.arg}
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        out: List[Tuple[Optional[int], str]] = []
        nums = kws.get("static_argnums")
        if nums is not None:
            elts = nums.elts if isinstance(
                nums, (ast.Tuple, ast.List)
            ) else [nums]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(
                    e.value, int
                ) and 0 <= e.value < len(params):
                    out.append((e.value, params[e.value]))
        names = kws.get("static_argnames")
        if names is not None:
            elts = names.elts if isinstance(
                names, (ast.Tuple, ast.List)
            ) else [names]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(
                    e.value, str
                ):
                    pos = (
                        params.index(e.value) if e.value in params else None
                    )
                    out.append((pos, e.value))
        return out

    def call_arg(
        self, call: ast.Call, pos: Optional[int], pname: str
    ) -> Optional[ast.expr]:
        for kw in call.keywords:
            if kw.arg == pname:
                return kw.value
        if pos is not None and pos < len(call.args):
            arg = call.args[pos]
            if isinstance(arg, ast.Starred):
                return None
            return arg
        return None

    # -- finiteness classification --------------------------------------------

    def classify(
        self,
        expr: ast.expr,
        encl: Optional[ast.AST],
        depth: int = 0,
        seen: Optional[set] = None,
    ) -> Optional[str]:
        """None if ``expr`` provably draws from a finite domain, else the
        reason it is runtime-derived. ``encl`` is the function the
        expression appears in (its parameters trace to call sites)."""
        if seen is None:
            seen = set()
        if depth > 4:
            return "call-site trace exceeded depth 4"
        if isinstance(expr, ast.Constant):
            return None  # float statics are the hazard rule's business
        if isinstance(expr, (ast.Tuple, ast.List)):
            for e in expr.elts:
                r = self.classify(e, encl, depth, seen)
                if r:
                    return r
            return None
        if isinstance(expr, ast.UnaryOp):
            return self.classify(expr.operand, encl, depth, seen)
        if isinstance(expr, ast.BinOp):
            return self.classify(expr.left, encl, depth, seen) or \
                self.classify(expr.right, encl, depth, seen)
        if isinstance(expr, ast.IfExp):
            return self.classify(expr.body, encl, depth, seen) or \
                self.classify(expr.orelse, encl, depth, seen)
        if isinstance(expr, ast.Subscript):
            return self.classify(expr.value, encl, depth, seen)
        if isinstance(expr, ast.Attribute):
            parts = []
            cur: ast.AST = expr
            while isinstance(cur, ast.Attribute):
                parts.append(cur.attr)
                cur = cur.value
            if "shape" in parts:
                return None  # array shapes are engine-shape-derived
            if isinstance(cur, ast.Name) and (
                cur.id in self.table.finite_attr_bases
            ):
                return None
            src = dotted_name(expr) or "<attribute>"
            return (
                f"`{src}` is not rooted at a declared config source "
                f"({', '.join(sorted(self.table.finite_attr_bases))})"
            )
        if isinstance(expr, ast.Call):
            fname = dotted_name(expr.func)
            if fname in _FINITE_BUILTINS:
                for a in expr.args:
                    r = self.classify(a, encl, depth, seen)
                    if r:
                        return r
                return None
            return f"value produced by call to `{fname or '<expr>'}`"
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in self.table.finite_domains:
                return None
            if encl is not None:
                params = [
                    a.arg
                    for a in encl.args.posonlyargs + encl.args.args
                ]
                if name in params:
                    return self._classify_param(
                        encl, params.index(name), name, depth, seen
                    )
            rhs = self.module_assigns.get(name)
            if rhs is not None:
                return self.classify(rhs, None, depth + 1, seen)
            return (
                f"`{name}` is neither a declared finite domain, a "
                "traceable parameter, nor a module constant"
            )
        return f"unclassifiable expression at line {expr.lineno}"

    def _classify_param(
        self, fn: ast.AST, pos: int, name: str, depth: int, seen: set
    ) -> Optional[str]:
        key = (fn.name, name)
        if key in seen:
            return None  # cycle: judged by the other paths
        seen.add(key)
        sites = [
            c for c in self.calls_by_name.get(fn.name, ())
            if self.enclosing_fn(c) is not fn
        ]
        if not sites:
            return (
                f"parameter `{name}` of `{fn.name}` has no declared "
                "finite domain and no same-module call site to trace"
            )
        for site in sites:
            arg = self.call_arg(site, pos, name)
            if arg is None:
                continue
            r = self.classify(
                arg, self.enclosing_fn(site), depth + 1, seen
            )
            if r:
                return (
                    f"via `{fn.name}` call at line {site.lineno}: {r}"
                )
        return None

    # -- closure-capture support ----------------------------------------------

    def array_consts_in_scope(self, fn: ast.AST) -> Dict[str, int]:
        """Names assigned array-producing expressions in the module scope
        or any enclosing function scope of ``fn`` -> assignment line."""
        out: Dict[str, int] = {}

        def is_array(expr: ast.expr) -> bool:
            if isinstance(expr, ast.Call):
                d = dotted_name(expr.func) or ""
                return any(d.startswith(p) for p in _ARRAY_ROOTS)
            return False

        for name, rhs in self.module_assigns.items():
            if is_array(rhs):
                out[name] = rhs.lineno
        cur = getattr(fn, "_orion_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for node in ast.walk(cur):
                    if isinstance(node, ast.Assign) and len(
                        node.targets
                    ) == 1 and isinstance(node.targets[0], ast.Name) \
                            and is_array(node.value):
                        out.setdefault(
                            node.targets[0].id, node.value.lineno
                        )
            cur = getattr(cur, "_orion_parent", None)
        return out


def _model(ctx: ModuleContext, table: ProgramTable) -> ProgramModel:
    cached = getattr(ctx, "_orion_program_model", None)
    if cached is None or cached.table is not table:
        cached = ProgramModel(ctx, table)
        ctx._orion_program_model = cached  # type: ignore[attr-defined]
    return cached


# -- the per-module rules ------------------------------------------------------


class _TierERule:
    def __init__(self, table: Optional[ProgramTable] = None):
        self._table = table

    @property
    def table(self) -> ProgramTable:
        return self._table if self._table is not None else \
            load_program_table()

    def _skip(self, ctx: ModuleContext) -> bool:
        return ctx.is_test


class UnregisteredJitRule(_TierERule):
    id = RULE_UNREGISTERED
    title = "jit/shard_map site with no analysis/programs.py declaration"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if self._skip(ctx):
            return
        model = _model(ctx, self.table)
        for fn, _dec in model.jit_defs:
            if self.table.decl_at(ctx.path, fn.name) is None:
                yield Finding(
                    self.id, ctx.path, fn.lineno,
                    f"jitted function `{fn.name}` is not a declared "
                    "program — every executable the fleet compiles must "
                    "have a ProgramDecl row in analysis/programs.py "
                    "(section decode/solo/setup/training) so the AOT "
                    "store can plan it",
                )
        for call, qualname in model.bare_sites:
            if self.table.decl_at(ctx.path, qualname) is None:
                what = dotted_name(call.func) or "jit"
                yield Finding(
                    self.id, ctx.path, call.lineno,
                    f"`{what}` call site in `{qualname}` is not a "
                    "declared program — declare the enclosing function "
                    "in analysis/programs.py or route through a "
                    "registered wrapper",
                )


class UnboundedStaticKeyRule(_TierERule):
    id = RULE_UNBOUNDED
    title = "static jit argument outside every declared finite domain"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if self._skip(ctx):
            return
        model = _model(ctx, self.table)
        for fn, dec in model.jit_defs:
            decl = self.table.decl_at(ctx.path, fn.name)
            if decl is None:
                continue  # unregistered-jit owns that finding
            sp = model.static_params(fn, dec)
            names = tuple(p for _, p in sp)
            if tuple(decl.static_args) != names:
                yield Finding(
                    self.id, ctx.path, fn.lineno,
                    f"`{fn.name}` static signature {names!r} drifted "
                    f"from the declared static_args "
                    f"{tuple(decl.static_args)!r} — update the "
                    "ProgramDecl so the key-space claim matches the code",
                )
                continue
            if decl.keyspace == "open":
                continue
            for pos, pname in sp:
                if pname in self.table.finite_domains:
                    continue
                sites = model.calls_by_name.get(fn.name, ())
                if not sites:
                    yield Finding(
                        self.id, ctx.path, fn.lineno,
                        f"static arg `{pname}` of `{fn.name}` has no "
                        "declared finite domain "
                        "(programs.FINITE_DOMAINS) and no same-module "
                        "call site to trace",
                    )
                    continue
                for site in sites:
                    arg = model.call_arg(site, pos, pname)
                    if arg is None:
                        continue
                    reason = model.classify(
                        arg, model.enclosing_fn(site)
                    )
                    if reason:
                        yield Finding(
                            self.id, ctx.path, site.lineno,
                            f"static arg `{pname}` of `{fn.name}` is "
                            f"runtime-derived here: {reason} — an "
                            "unbounded key space means a cold replica "
                            "pays surprise compiles mid-traffic; pass a "
                            "declared finite value or add the domain to "
                            "programs.FINITE_DOMAINS with a rationale",
                        )


class RecompileHazardRule(_TierERule):
    id = RULE_HAZARD
    title = "silent compile-cache blowup shape"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if self._skip(ctx):
            return
        model = _model(ctx, self.table)
        # (a) closure capture of arrays in a jitted function
        for fn, dec in model.jit_defs:
            arrays = model.array_consts_in_scope(fn)
            if not arrays:
                continue
            local = set(
                a.arg for a in fn.args.posonlyargs + fn.args.args
            )
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            local.add(t.id)
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load
                ) and node.id in arrays and node.id not in local:
                    yield Finding(
                        self.id, ctx.path, node.lineno,
                        f"jitted `{fn.name}` closes over array "
                        f"`{node.id}` (assigned at line "
                        f"{arrays[node.id]}) — the value is baked into "
                        "the trace and every rebind retraces; pass it "
                        "as an argument",
                    )
        # (b)+(c) hazardous expressions feeding static positions
        for fn, dec in model.jit_defs:
            for pos, pname in model.static_params(fn, dec):
                for site in model.calls_by_name.get(fn.name, ()):
                    arg = model.call_arg(site, pos, pname)
                    if arg is None:
                        continue
                    for f in self._static_expr_hazards(
                        ctx, fn.name, pname, site, arg
                    ):
                        yield f
        # (d) functools.partial re-wrapping a registered wrapper
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call):
                continue
            fname = dotted_name(call.func)
            if fname not in ("partial", "functools.partial"):
                continue
            if not call.args or not isinstance(call.args[0], ast.Name):
                continue
            target = call.args[0].id
            if target not in self.table.qualnames:
                continue
            if model.enclosing_fn(call) is None:
                continue  # module-level partial: one object, one cache
            yield Finding(
                self.id, ctx.path, call.lineno,
                f"functools.partial re-wraps registered jit `{target}` "
                "inside a function body — each call builds a fresh "
                "callable, so re-jitting or tracing it forks the "
                "compile cache; call the registered wrapper directly",
            )

    def _static_expr_hazards(self, ctx, fname, pname, site, arg):
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Constant) and isinstance(
                sub.value, float
            ):
                yield Finding(
                    self.id, ctx.path, site.lineno,
                    f"float literal {sub.value!r} feeds static arg "
                    f"`{pname}` of `{fname}` — float keys accumulate "
                    "near-duplicate cache entries; use an int or a "
                    "declared enum",
                )
            elif isinstance(sub, ast.Call):
                d = dotted_name(sub.func)
                attr = sub.func.attr if isinstance(
                    sub.func, ast.Attribute
                ) else ""
                if d == "float":
                    yield Finding(
                        self.id, ctx.path, site.lineno,
                        f"float() feeds static arg `{pname}` of "
                        f"`{fname}` — float keys accumulate "
                        "near-duplicate cache entries",
                    )
                elif attr in ("keys", "values", "items") or d in (
                    "set", "frozenset"
                ):
                    yield Finding(
                        self.id, ctx.path, site.lineno,
                        f"dict/set iteration feeds static arg "
                        f"`{pname}` of `{fname}` — iteration order is "
                        "insertion/hash-dependent, so equal contents "
                        "can produce distinct static keys; sort into a "
                        "tuple first",
                    )


def program_rules(table: Optional[ProgramTable] = None) -> List:
    return [
        UnregisteredJitRule(table),
        UnboundedStaticKeyRule(table),
        RecompileHazardRule(table),
    ]


# -- repo-level checks: registry, plan, donation -------------------------------


def _repo_root(root: str = "") -> str:
    return root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def registry_drift_findings(
    table: Optional[ProgramTable] = None, root: str = ""
) -> List[Finding]:
    """generate.DECODE_PROGRAMS (parsed from the AST, never imported)
    diffed against the declared decode section — both directions."""
    table = table or load_program_table()
    root = _repo_root(root)
    path = _decls.GENERATE
    try:
        with open(os.path.join(root, path), encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError) as e:
        return [Finding(RULE_PLAN, path, 0,
                        f"cannot parse DECODE_PROGRAMS registry: {e}")]
    reg: Dict[str, str] = {}
    lineno = 0
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "DECODE_PROGRAMS" and \
                isinstance(node.value, ast.Dict):
            lineno = node.lineno
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(
                    v, ast.Name
                ):
                    reg[k.value] = v.id
    out: List[Finding] = []
    if not reg:
        return [Finding(RULE_PLAN, path, 0,
                        "DECODE_PROGRAMS dict not found — the serving "
                        "program registry moved; update Tier E")]
    declared = {d.name: d for d in table.section("decode")}
    for name, qual in sorted(reg.items()):
        d = declared.get(name)
        if d is None:
            out.append(Finding(
                RULE_PLAN, path, lineno,
                f"DECODE_PROGRAMS entry `{name}` has no decode-section "
                "ProgramDecl — declare it (with plan applicability) in "
                "analysis/programs.py",
            ))
        elif d.qualname != qual:
            out.append(Finding(
                RULE_PLAN, path, lineno,
                f"DECODE_PROGRAMS maps `{name}` to `{qual}` but the "
                f"declaration names `{d.qualname}`",
            ))
    for name in sorted(set(declared) - set(reg)):
        out.append(Finding(
            RULE_PLAN, path, lineno,
            f"declared decode program `{name}` is missing from "
            "DECODE_PROGRAMS — a dead declaration mutes the audit",
        ))
    return out


# canonical-footprint lowering reports, memoized process-wide: the
# lowering half of Tier E costs seconds once and nothing after (the
# tier's <45s budget is pinned in tests/test_analysis.py)
_PLAN_MEMO: Dict[str, Dict[str, Any]] = {}

_IDENT_FIELDS = (
    "kind", "slots", "chunk", "bucket", "prefill_chunk", "qmode", "tp",
    "spec_depth",
)


def _ident(entry: Dict[str, Any]) -> Tuple:
    return tuple(
        (k, entry[k]) for k in _IDENT_FIELDS if k in entry
    )


def _fp_args(fp: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in fp.items() if k != "expect_programs"}


def _default_inventory(fp: Dict[str, Any], lower: bool) -> Dict[str, Any]:
    from orion_tpu.aot import decode_plan
    from orion_tpu.models.configs import get_config

    key = repr(sorted(fp.items())) + f" lower={lower}"
    got = _PLAN_MEMO.get(key)
    if got is None:
        got = decode_plan(
            get_config("tiny"), compile_step=False, lower=lower,
            **_fp_args(fp),
        )
        _PLAN_MEMO[key] = got
    return got


def plan_drift_findings(
    table: Optional[ProgramTable] = None,
    footprints=None,
    inventory_fn=None,
    lower: bool = True,
) -> List[Finding]:
    """Diff ``aot.decode_plan``'s inventory against the declared universe
    per check footprint; with ``lower=True`` the FIRST footprint is also
    lowered (memoized) so a planned program that fails to lower is a
    finding. ``inventory_fn(footprint) -> report`` injects a plan for
    tests (a deliberately stale one must produce findings)."""
    table = table or load_program_table()
    if footprints is None:
        footprints = _decls.CHECK_FOOTPRINTS
    out: List[Finding] = []
    for i, fp in enumerate(footprints):
        do_lower = lower and i == 0 and inventory_fn is None
        try:
            report = (
                inventory_fn(fp) if inventory_fn is not None
                else _default_inventory(fp, do_lower)
            )
        except Exception as e:  # the audit must never crash on the plan
            out.append(Finding(
                RULE_PLAN, "<decode-plan>", 0,
                f"decode_plan failed for footprint {_fp_args(fp)!r}: "
                f"{type(e).__name__}: {e}",
            ))
            continue
        expected = _decls.expected_decode_universe(
            slots=fp["slots"], chunk=fp["chunk"],
            prefill_buckets=fp.get("prefill_buckets", ()),
            prefill_chunk=report.get(
                "prefill_chunk_aligned", fp.get("prefill_chunk", 0)
            ),
            qmode=fp.get("qmode", "off"), tp=fp.get("tp", 1),
            spec_depth=fp.get("spec_depth", 0), decls=table.decls,
        )
        want = fp.get("expect_programs")
        if want is not None and len(expected) != want:
            out.append(Finding(
                RULE_PLAN, "<decode-plan>", 0,
                f"declared universe for footprint {_fp_args(fp)!r} has "
                f"{len(expected)} programs, CHECK_FOOTPRINTS expects "
                f"{want} — update the declaration",
            ))
        inv = {_ident(p): p for p in report.get("programs", ())}
        exp = {_ident(e): e for e in expected}
        for key in sorted(set(exp) - set(inv)):
            out.append(Finding(
                RULE_PLAN, "<decode-plan>", 0,
                f"declared program missing from decode_plan inventory "
                f"(footprint {_fp_args(fp)!r}): {dict(key)!r} — a cold "
                "replica would compile it mid-traffic",
            ))
        for key in sorted(set(inv) - set(exp)):
            out.append(Finding(
                RULE_PLAN, "<decode-plan>", 0,
                f"decode_plan lists a program outside the declared "
                f"universe (footprint {_fp_args(fp)!r}): {dict(key)!r} "
                "— a phantom entry breaks the warm-start contract",
            ))
        if do_lower:
            for p in report.get("programs", ()):
                if p.get("error"):
                    out.append(Finding(
                        RULE_PLAN, "<decode-plan>", 0,
                        f"planned program {p.get('kind')} fails to "
                        f"lower: {p['error']}",
                    ))
    return out


def donation_drift_findings(
    table: Optional[ProgramTable] = None,
    root: str = "",
    golden_dir: Optional[str] = None,
) -> List[Finding]:
    """Three-way donate_argnums check per declared program: decorator AST
    vs declaration vs the golden snapshots' recorded donation counts."""
    table = table or load_program_table()
    root = _repo_root(root)
    if golden_dir is None:
        golden_dir = os.path.join(os.path.dirname(__file__), "golden")
    out: List[Finding] = []
    trees: Dict[str, Optional[ast.AST]] = {}
    for d in table.decls:
        tree = trees.get(d.module, False)
        if tree is False:
            try:
                with open(
                    os.path.join(root, d.module), encoding="utf-8"
                ) as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                tree = None
            trees[d.module] = tree
        if tree is not None:
            for fn in ast.walk(tree):
                if isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and fn.name == d.qualname and jit_decorations(fn):
                    dec = jit_decorations(fn)[0]
                    donated: Tuple[int, ...] = ()
                    if isinstance(dec, ast.Call):
                        for kw in dec.keywords:
                            if kw.arg == "donate_argnums":
                                v = kw.value
                                elts = v.elts if isinstance(
                                    v, (ast.Tuple, ast.List)
                                ) else [v]
                                donated = tuple(
                                    e.value for e in elts
                                    if isinstance(e, ast.Constant)
                                )
                    if donated != tuple(d.donate_argnums):
                        out.append(Finding(
                            RULE_DONATION, d.module, fn.lineno,
                            f"`{d.qualname}` donates {donated!r} but "
                            "the declaration says "
                            f"{tuple(d.donate_argnums)!r} — a dropped "
                            "donation is a silent memory regression; "
                            "fix the code or the ProgramDecl",
                        ))
                    break
        for g in d.goldens:
            gpath = os.path.join(golden_dir, f"{g}.json")
            rel = normalize_path(gpath, root)
            try:
                with open(gpath, encoding="utf-8") as f:
                    snap = json.load(f)
            except (OSError, ValueError):
                out.append(Finding(
                    RULE_DONATION, rel, 0,
                    f"golden snapshot `{g}` pinning `{d.name}` donation "
                    "is missing/unreadable — regenerate with "
                    "--update-golden",
                ))
                continue
            got = int(
                snap.get("donation", {}).get("donated_args", 0)
            )
            if bool(got) != bool(d.donate_argnums):
                out.append(Finding(
                    RULE_DONATION, rel, 0,
                    f"golden `{g}` records {got} donated args but the "
                    f"declaration for `{d.name}` says "
                    f"{tuple(d.donate_argnums)!r} — donation drifted "
                    "between the compiled artifact and the registry",
                ))
    return out


# -- tier entry points ---------------------------------------------------------


def audit_programs(
    paths=None,
    root: str = "",
    baseline: Tuple[BaselineEntry, ...] = (),
    keep_suppressed: bool = False,
    table: Optional[ProgramTable] = None,
    lower: bool = True,
    golden_dir: Optional[str] = None,
) -> List[Finding]:
    """Run Tier E over the program packages (or explicit paths)."""
    root = _repo_root(root)
    if paths is None:
        paths = [os.path.join(root, p) for p in TIER_E_PATHS]
    fs = lint_paths(
        paths, rules=program_rules(table), root=root, keep_suppressed=True,
    )
    fs += registry_drift_findings(table, root)
    fs += donation_drift_findings(table, root, golden_dir)
    fs += plan_drift_findings(table, lower=lower)
    fs.sort(key=lambda f: (f.path, f.line, f.rule))
    if keep_suppressed:
        return annotate_baseline(fs, baseline)
    return [
        f for f in apply_baseline(fs, baseline)
        if f.status != "suppressed"
    ]


def audit_source(
    source: str, path: str, table: Optional[ProgramTable] = None
) -> List[Finding]:
    """Tier E's per-module rules over one in-memory module (the test
    fixture entry point; the repo-level plan/donation checks are their
    own functions)."""
    from orion_tpu.analysis.lint import lint_source

    return lint_source(source, path, rules=program_rules(table))


__all__ = [
    "ALL_PROGRAM_CHECKS", "ProgramModel", "ProgramTable",
    "audit_programs", "audit_source", "program_rules",
    "load_program_table", "registry_drift_findings",
    "plan_drift_findings", "donation_drift_findings", "TIER_E_PATHS",
    "RULE_UNREGISTERED", "RULE_UNBOUNDED", "RULE_HAZARD", "RULE_PLAN",
    "RULE_DONATION",
]
