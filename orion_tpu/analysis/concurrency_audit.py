"""Analysis Tier D — the concurrency auditor for the threaded serving
stack (``--tier concurrency``).

The declaration lives in :mod:`orion_tpu.serving.locks` (the
`parallel/budgets.py` idiom: contracts as data, next to the code): lock
sites + aliases, a partial acquisition ORDER, guarded-by fields, and
per-lock held-scope bans. This module walks the AST of every module in
the four threaded packages (`serving/`, `fleet/`, `obs/`,
`resilience/`) — never importing or executing them — computes an
interprocedural *held-locks-at-site* summary, and emits five rules:

``lock-order-inversion``
    acquiring lock A while B is held when the declared order (closed
    transitively) says A is an OUTER of B — the reversed path is the
    half of a deadlock cycle the other thread supplies.
``blocking-under-lock``
    a call matching a held lock's declared ban category (wire I/O under
    the router lock, disk/subprocess/sleep under the stats lock, a
    device sync under any obs lock — the sync set is obs-device-sync's
    classifier minus the bare float()/int() coercions, which only the
    obs package itself bans).
``unguarded-shared-field``
    a field declared guarded-by L assigned without L held. ``__init__``
    (and any declared construction-path method) and module-level
    statements are exempt; matching covers subscript stores
    (``self._slots[i] = ...``) and tuple-unpacking targets.
``undeclared-lock``
    a ``threading.Lock/RLock/Condition`` constructed in an audited
    module with no matching declaration — the hierarchy cannot rot
    silently as ROADMAP items add threads.
``lock-scope-creep``
    a strict-scope lock (router.lock, watchdog.lock, inject.plan) held
    across a call the auditor has no summary for: not a builtin, not a
    CapWords constructor, not a container method, not same-module code,
    not in the lock's declared ``allow_calls``. Holding a bookkeeping
    lock across unknown code is how "covers bookkeeping only" rots.

**Held-lock model.** Within a function the walk is statement-ordered:
``with <lock>:`` scopes push/pop, bare ``.acquire()``/``.release()``
calls toggle from their statement onward (a conditional acquire is
over-approximated as held for the rest of the function — lint-grade and
deliberate). Interprocedurally, every same-module call edge resolvable
by name (bare names to module/nested defs, ``self.meth`` to same-class
methods — the `signal-unsafe-handler` closure idiom scaled up) feeds a
fixpoint: a callee's entry held-set is the union over its call sites of
the caller's held-set there. Bodies of nested ``def``/``lambda`` are
excluded from the enclosing scope (they run when *called*, which the
edge fixpoint models) — so a callback defined under a lock is not
falsely "under" it. Declared ``decorators`` (batching's
``@_serialized``) seed the wrapped method's entry set, since the
``with`` lives in the wrapper's AST, not the method's.

**Lock identity.** An expression maps to a declared node by (module,
enclosing scope, attr) against the declaration and its aliases; failing
that, by (module, attr) when unique within the module; failing that, by
attr when unique across the whole table (this is what lets router code
name ``replica._state_lock``). The alias list is how the shared Server⇄
HealthMachine⇄MetricsRegistry RLock stays ONE node. Everything the
auditor cannot map is simply not tracked — and if it was constructed in
scope, ``undeclared-lock`` already flagged it.

Findings ride the standard pipeline: ``# orion: noqa[rule-id]``,
baseline.json rationales, ``--format json``. The auditor never imports
or executes the audited code — zero traces, compiles, or device syncs —
and the declaration module is loaded by FILE path, bypassing
``serving/__init__`` (which imports the whole engine stack), so
``--tier concurrency`` stays a sub-second pure-AST pass.
"""

from __future__ import annotations

import ast
import builtins
import importlib.util
import os
import sys
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from orion_tpu.analysis.findings import BaselineEntry, Finding
from orion_tpu.analysis.lint import ModuleContext, dotted_name, lint_paths
from orion_tpu.analysis.rules.obs import _SYNC_ATTRS, _SYNC_DOTTED

RULE_ORDER = "lock-order-inversion"
RULE_BLOCKING = "blocking-under-lock"
RULE_UNGUARDED = "unguarded-shared-field"
RULE_UNDECLARED = "undeclared-lock"
RULE_CREEP = "lock-scope-creep"

ALL_CONCURRENCY_CHECKS = (
    RULE_ORDER, RULE_BLOCKING, RULE_UNGUARDED, RULE_UNDECLARED, RULE_CREEP,
)

# the four packages in Tier D scope (ISSUE 16): everything with a thread
TIER_D_PACKAGES = (
    "orion_tpu/serving", "orion_tpu/fleet", "orion_tpu/obs",
    "orion_tpu/resilience",
)

# container/primitive methods that cannot transfer control to foreign
# code — safe under any strict scope (dict/list/deque/set/str/queue/
# event bookkeeping is exactly what a bookkeeping lock exists for)
_DATA_METHODS = frozenset({
    "append", "appendleft", "extend", "pop", "popleft", "clear", "add",
    "discard", "remove", "insert", "count", "index", "sort", "reverse",
    "copy", "update", "setdefault", "get", "keys", "values", "items",
    "join", "split", "rsplit", "strip", "startswith", "endswith",
    "format", "encode", "decode", "isalnum", "lower", "upper",
    "qsize", "empty", "full", "put_nowait", "get_nowait",
    "is_set", "locked", "total_seconds",
})

# dotted calls safe under any strict scope: host clock reads
_SAFE_DOTTED = frozenset({
    "time.monotonic", "time.time", "time.perf_counter",
    "time.monotonic_ns", "time.perf_counter_ns",
})

# the repo-wide injectable-clock idiom: ``self._clock()`` is by contract
# a cheap host time source (time.monotonic or a test's fake)
_SAFE_SELF_ATTRS = frozenset({"_clock"})

_BUILTIN_NAMES = frozenset(dir(builtins))


# -- declaration loading -------------------------------------------------------


class LockTable:
    """The declaration (serving/locks.py) indexed for AST resolution."""

    def __init__(self, locks: Dict, order, bans: Dict):
        self.locks = locks
        self.order = tuple(order)
        self.bans = bans
        # (module, scope, attr) -> node; (module, attr) -> nodes;
        # attr -> nodes
        self._exact: Dict[Tuple[str, str, str], str] = {}
        self._by_module_attr: Dict[Tuple[str, str], Set[str]] = {}
        self._by_attr: Dict[str, Set[str]] = {}
        self._decorators: Dict[Tuple[str, str], str] = {}
        for name, decl in locks.items():
            for site in (decl.site, *decl.aliases):
                self._exact[(site.module, site.scope, site.attr)] = name
                self._by_module_attr.setdefault(
                    (site.module, site.attr), set()
                ).add(name)
                self._by_attr.setdefault(site.attr, set()).add(name)
            for deco in decl.decorators:
                self._decorators[(decl.site.module, deco)] = name
        # transitive closure of the declared partial order:
        # inners[A] = every node A is an OUTER of
        self.inners: Dict[str, Set[str]] = {}
        for outer, inner in self.order:
            self.inners.setdefault(outer, set()).add(inner)
        changed = True
        while changed:
            changed = False
            for outer, inner_set in list(self.inners.items()):
                for inner in list(inner_set):
                    for deeper in self.inners.get(inner, ()):
                        if deeper not in inner_set:
                            inner_set.add(deeper)
                            changed = True

    def decl(self, name: str):
        return self.locks[name]

    def node_for(self, module: str, scope: str, attr: str) -> Optional[str]:
        """Resolve a lock-valued expression to a declared node name; see
        the module docstring for the precedence ladder."""
        hit = self._exact.get((module, scope, attr))
        if hit is not None:
            return hit
        hits = self._by_module_attr.get((module, attr), ())
        if len(hits) == 1:
            return next(iter(hits))
        hits = self._by_attr.get(attr, ())
        if len(hits) == 1:
            return next(iter(hits))
        return None

    def decorator_lock(self, module: str, deco: str) -> Optional[str]:
        return self._decorators.get((module, deco))


_TABLE: Optional[LockTable] = None
_LOCKS_MODULE = None


def load_locks_module():
    """Load serving/locks.py by FILE, not package import: the lint pass
    must stay free of serving/__init__ (which imports the whole engine
    stack). This is also Tier A's doorway into the declaration — the
    unbounded-wait rule's obs widened scope reads ``obs_lock_attrs()``
    from here rather than keeping a second hand-maintained list."""
    global _LOCKS_MODULE
    if _LOCKS_MODULE is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "serving", "locks.py",
        )
        spec = importlib.util.spec_from_file_location(
            "_orion_tpu_lock_decls", path
        )
        mod = importlib.util.module_from_spec(spec)
        # dataclasses resolves string annotations through sys.modules, so
        # the file-loaded module must be registered before exec
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)  # type: ignore[union-attr]
        _LOCKS_MODULE = mod
    return _LOCKS_MODULE


def load_lock_table() -> LockTable:
    global _TABLE
    if _TABLE is None:
        mod = load_locks_module()
        _TABLE = LockTable(mod.LOCKS, mod.ORDER, mod.BAN_CATEGORIES)
    return _TABLE


# -- the per-module model ------------------------------------------------------


def _receiver_parts(node: ast.AST) -> Optional[List[str]]:
    """``self._registry._lock`` -> ['self', '_registry', '_lock']."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return list(reversed(parts))
    return None


class _FnInfo:
    def __init__(self, node: ast.AST, scope: str):
        self.node = node
        self.scope = scope  # enclosing class/function name, '' = module
        self.name = node.name  # type: ignore[attr-defined]
        # events carry the LOCAL held-set; entry_held is unioned in later
        self.calls: List[Tuple[ast.Call, FrozenSet[str]]] = []
        self.acquires: List[Tuple[str, int, FrozenSet[str]]] = []
        self.writes: List[Tuple[str, int, FrozenSet[str]]] = []
        # resolved same-module call edges: (callee key, held at site)
        self.edges: List[Tuple[str, FrozenSet[str]]] = []
        self.entry_held: Set[str] = set()


class ConcurrencyModel:
    """Everything the five rules need for one module, computed once."""

    def __init__(self, ctx: ModuleContext, table: LockTable):
        self.ctx = ctx
        self.table = table
        self.fns: Dict[str, _FnInfo] = {}  # key = f"{scope}.{name}"
        self._class_methods: Dict[str, Set[str]] = {}
        self._module_defs: Dict[str, List[str]] = {}  # name -> fn keys
        self._collect()
        self._fixpoint()

    # -- collection -----------------------------------------------------------

    def _collect(self) -> None:
        for node, scope in self._iter_defs(self.ctx.tree, ""):
            info = _FnInfo(node, scope)
            key = f"{scope}.{info.name}"
            # later defs of the same key win nothing; keep the first and
            # index duplicates under a suffixed key so events survive
            while key in self.fns:
                key += "'"
            self.fns[key] = info
            self._module_defs.setdefault(info.name, []).append(key)
            if scope:
                self._class_methods.setdefault(scope, set()).add(info.name)
        for info in self.fns.values():
            held: Set[str] = set()
            for deco in getattr(info.node, "decorator_list", ()):
                name = dotted_name(deco)
                if name is None and isinstance(deco, ast.Call):
                    name = dotted_name(deco.func)
                if name:
                    lock = self.table.decorator_lock(
                        self.ctx.path, name.rsplit(".", 1)[-1]
                    )
                    if lock:
                        info.entry_held.add(lock)
            self._walk_block(info.node.body, held, info)  # type: ignore

    def _iter_defs(self, tree: ast.AST, scope: str):
        """Yield (def node, enclosing scope name) for every function in
        the module, including methods and nested defs."""
        for node in ast.iter_child_nodes(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node, scope
                yield from self._iter_defs(node, node.name)
            elif isinstance(node, ast.ClassDef):
                yield from self._iter_defs(node, node.name)
            elif isinstance(node, (ast.If, ast.Try, ast.With, ast.For,
                                   ast.While)):
                yield from self._iter_defs(node, scope)

    # -- lock expression mapping ----------------------------------------------

    def map_lock(self, expr: ast.AST, scope: str) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return self.table.node_for(self.ctx.path, scope, expr.id)
        parts = _receiver_parts(expr)
        if not parts:
            return None
        attr = parts[-1]
        if parts[0] == "self" and len(parts) == 2:
            return self.table.node_for(self.ctx.path, scope, attr)
        return self.table.node_for(self.ctx.path, "", attr) or (
            self.table.node_for(self.ctx.path, scope, attr)
        )

    # -- the statement walk ---------------------------------------------------

    def _iter_calls(self, root: ast.AST) -> Iterator[ast.Call]:
        """Call nodes of an expression, excluding nested def/lambda
        bodies (they execute when called, not here)."""
        stack = [root]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    def _scan_expr(self, expr: ast.AST, held: Set[str],
                   info: _FnInfo) -> None:
        """Record call events + resolve edges + apply acquire/release."""
        for call in self._iter_calls(expr):
            frozen = frozenset(held)
            info.calls.append((call, frozen))
            # same-module edges
            if isinstance(call.func, ast.Name):
                for key in self._module_defs.get(call.func.id, ()):
                    info.edges.append((key, frozen))
            elif isinstance(call.func, ast.Attribute):
                parts = _receiver_parts(call.func)
                if (parts and parts[0] == "self" and len(parts) == 2
                        and info.scope
                        and call.func.attr
                        in self._class_methods.get(info.scope, ())):
                    for key in self._module_defs.get(call.func.attr, ()):
                        if self.fns[key].scope == info.scope:
                            info.edges.append((key, frozen))
                # bare acquire/release toggles
                if parts and call.func.attr in ("acquire", "release"):
                    lock = self.map_lock(call.func.value, info.scope)
                    if lock is not None:
                        if call.func.attr == "acquire":
                            info.acquires.append(
                                (lock, call.lineno, frozenset(held))
                            )
                            held.add(lock)
                        else:
                            held.discard(lock)

    def _record_writes(self, target: ast.AST, lineno: int, held: Set[str],
                       info: _FnInfo) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_writes(elt, lineno, held, info)
            return
        base = target
        while isinstance(base, ast.Subscript):
            base = base.value
        if isinstance(base, ast.Attribute):
            info.writes.append((base.attr, lineno, frozenset(held)))
        elif isinstance(base, ast.Name) and isinstance(target, ast.Name):
            # module-global writes (flight.configure's _default swap)
            info.writes.append((base.id, lineno, frozenset(held)))

    def _walk_block(self, stmts, held: Set[str], info: _FnInfo) -> None:
        for st in stmts:
            self._walk_stmt(st, held, info)

    def _walk_stmt(self, st: ast.stmt, held: Set[str],
                   info: _FnInfo) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # separate scope; edges model when it actually runs
        if isinstance(st, ast.With):
            pushed: List[str] = []
            for item in st.items:
                self._scan_expr(item.context_expr, held, info)
                lock = self.map_lock(item.context_expr, info.scope)
                if lock is not None:
                    info.acquires.append(
                        (lock, st.lineno, frozenset(held))
                    )
                    if lock not in held:
                        held.add(lock)
                        pushed.append(lock)
            self._walk_block(st.body, held, info)
            for lock in pushed:
                held.discard(lock)
            return
        if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = getattr(st, "value", None)
            if value is not None:
                self._scan_expr(value, held, info)
            targets = (
                st.targets if isinstance(st, ast.Assign) else [st.target]
            )
            for t in targets:
                self._record_writes(t, st.lineno, held, info)
                self._scan_expr(t, held, info)  # subscript index calls
            return
        if isinstance(st, ast.Try):
            self._walk_block(st.body, held, info)
            for h in st.handlers:
                self._walk_block(h.body, held, info)
            self._walk_block(st.orelse, held, info)
            self._walk_block(st.finalbody, held, info)
            return
        if isinstance(st, (ast.If, ast.While)):
            self._scan_expr(st.test, held, info)
            self._walk_block(st.body, held, info)
            self._walk_block(getattr(st, "orelse", []), held, info)
            return
        if isinstance(st, ast.For):
            self._scan_expr(st.iter, held, info)
            self._walk_block(st.body, held, info)
            self._walk_block(st.orelse, held, info)
            return
        # generic statement: scan every embedded expression
        for field_val in ast.iter_child_nodes(st):
            if isinstance(field_val, ast.expr):
                self._scan_expr(field_val, held, info)

    # -- the interprocedural fixpoint -----------------------------------------

    def _fixpoint(self) -> None:
        changed = True
        while changed:
            changed = False
            for info in self.fns.values():
                base = info.entry_held
                for callee_key, held in info.edges:
                    callee = self.fns.get(callee_key)
                    if callee is None:
                        continue
                    add = (held | base) - callee.entry_held
                    if add:
                        callee.entry_held |= add
                        changed = True

    # -- event views (entry_held folded in) -----------------------------------

    def iter_acquires(self):
        for info in self.fns.values():
            entry = frozenset(info.entry_held)
            for lock, lineno, held in info.acquires:
                yield info, lock, lineno, held | entry

    def iter_calls(self):
        for info in self.fns.values():
            entry = frozenset(info.entry_held)
            for call, held in info.calls:
                yield info, call, held | entry

    def iter_writes(self):
        for info in self.fns.values():
            entry = frozenset(info.entry_held)
            for field, lineno, held in info.writes:
                yield info, field, lineno, held | entry

    def is_same_module_callee(self, info: _FnInfo, call: ast.Call) -> bool:
        if isinstance(call.func, ast.Name):
            return bool(self._module_defs.get(call.func.id))
        if isinstance(call.func, ast.Attribute):
            parts = _receiver_parts(call.func)
            return bool(
                parts and parts[0] == "self" and len(parts) == 2
                and info.scope
                and call.func.attr
                in self._class_methods.get(info.scope, ())
            )
        return False


def _model(ctx: ModuleContext, table: LockTable) -> ConcurrencyModel:
    cached = getattr(ctx, "_orion_concurrency_model", None)
    if cached is None or cached.table is not table:
        cached = ConcurrencyModel(ctx, table)
        ctx._orion_concurrency_model = cached  # type: ignore[attr-defined]
    return cached


# -- ban matching --------------------------------------------------------------


def _device_sync_label(call: ast.Call) -> Optional[str]:
    """obs-device-sync's classifier, minus bare float()/int() coercion
    (those are only a sync when the operand is a device array, which
    the obs package bans structurally; under a non-obs lock they are
    ordinary host arithmetic)."""
    name = dotted_name(call.func)
    if name in _SYNC_DOTTED:
        return f"{name}()"
    if name and name.split(".", 1)[0] in ("jax", "jnp"):
        return f"{name}()"
    if isinstance(call.func, ast.Attribute) and call.func.attr in _SYNC_ATTRS:
        return f".{call.func.attr}()"
    return None


def _match_ban(ban, call: ast.Call) -> Optional[str]:
    """The call shape that violates ``ban``, or None."""
    if ban.classifier == "device_sync":
        return _device_sync_label(call)
    name = dotted_name(call.func)
    if isinstance(call.func, ast.Name) and call.func.id in ban.names:
        return f"{call.func.id}()"
    if name:
        if name in ban.dotted:
            return f"{name}()"
        for prefix in ban.dotted_prefixes:
            if name.startswith(prefix):
                return f"{name}()"
    if isinstance(call.func, ast.Attribute) and call.func.attr in ban.attrs:
        parts = _receiver_parts(call.func)
        if parts != ["self", call.func.attr]:  # self.submit() = own method
            return f".{call.func.attr}()"
    return None


# -- the five rules ------------------------------------------------------------


class _TierDRule:
    def __init__(self, table: Optional[LockTable] = None):
        self._table = table

    @property
    def table(self) -> LockTable:
        return self._table if self._table is not None else load_lock_table()

    def _skip(self, ctx: ModuleContext) -> bool:
        return ctx.is_test


class LockOrderInversionRule(_TierDRule):
    id = RULE_ORDER
    title = "lock acquired against the declared acquisition order"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if self._skip(ctx):
            return
        model = _model(ctx, self.table)
        for info, lock, lineno, held in model.iter_acquires():
            for other in held:
                if other == lock:
                    continue  # reentrant re-acquire, not an inversion
                if other in self.table.inners.get(lock, ()):
                    yield Finding(
                        self.id, ctx.path, lineno,
                        f"acquires `{lock}` while holding `{other}`, but "
                        f"the declared order (serving/locks.py ORDER) "
                        f"makes `{lock}` an outer of `{other}` — this "
                        "path is one half of a deadlock cycle; take "
                        f"`{lock}` first or drop the nesting",
                    )


class BlockingUnderLockRule(_TierDRule):
    id = RULE_BLOCKING
    title = "banned blocking call in a held-lock scope"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if self._skip(ctx):
            return
        model = _model(ctx, self.table)
        for info, call, held in model.iter_calls():
            if not held:
                continue
            for lock in sorted(held):
                decl = self.table.decl(lock)
                for cat in decl.bans:
                    shape = _match_ban(self.table.bans[cat], call)
                    if shape is None:
                        continue
                    yield Finding(
                        self.id, ctx.path, call.lineno,
                        f"{shape} while holding `{lock}` violates its "
                        f"declared `{cat}` ban "
                        f"({self.table.bans[cat].note.split(';')[0]}) — "
                        "move the call outside the held scope",
                    )
                    break  # one finding per (call, lock)


class UnguardedSharedFieldRule(_TierDRule):
    id = RULE_UNGUARDED
    title = "declared guarded-by field written without its lock"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if self._skip(ctx):
            return
        # guards declared for THIS module: field name -> (lock, exempt)
        guards: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
        for name, decl in self.table.locks.items():
            for g in decl.guards:
                if g.module == ctx.path:
                    for field in g.fields:
                        guards[field] = (name, decl.guard_exempt)
        if not guards:
            return
        model = _model(ctx, self.table)
        for info, field, lineno, held in model.iter_writes():
            hit = guards.get(field)
            if hit is None:
                continue
            lock, exempt = hit
            if info.name in exempt:
                continue
            if lock not in held:
                yield Finding(
                    self.id, ctx.path, lineno,
                    f"`{field}` is declared guarded-by `{lock}` "
                    f"(serving/locks.py) but `{info.name}` writes it "
                    "without the lock held — take the lock, or declare "
                    "the construction path in guard_exempt",
                )


class UndeclaredLockRule(_TierDRule):
    id = RULE_UNDECLARED
    title = "lock constructed in scope but absent from the declaration"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if self._skip(ctx):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name not in (
                "threading.Lock", "threading.RLock", "threading.Condition"
            ):
                continue
            attr, scope = self._binding(ctx, node)
            if attr is None:
                continue
            if self.table.node_for(ctx.path, scope, attr) is None:
                yield Finding(
                    self.id, ctx.path, node.lineno,
                    f"{name}() bound to `{attr}` has no declaration in "
                    "serving/locks.py — declare its site, order, guards "
                    "and held-scope bans (the hierarchy must not rot "
                    "silently)",
                )

    @staticmethod
    def _binding(ctx: ModuleContext,
                 node: ast.AST) -> Tuple[Optional[str], str]:
        """The (attr, scope) a lock constructor is bound to: walk up to
        the nearest enclosing Assign; a ``self.X = threading.Lock()``
        target belongs to the enclosing CLASS scope, a bare-name target
        to the enclosing function (or '' at module level)."""
        assign = getattr(node, "_orion_parent", None)
        while assign is not None and not isinstance(
            assign, (ast.Assign, ast.AnnAssign)
        ):
            if isinstance(assign, ast.stmt):
                return None, ""  # not a binding (arg default, call, ...)
            assign = getattr(assign, "_orion_parent", None)
        if assign is None:
            return None, ""
        targets = (
            assign.targets if isinstance(assign, ast.Assign)
            else [assign.target]
        )
        target_attr = None
        self_attr = False
        for t in targets:
            if isinstance(t, ast.Attribute):
                target_attr = t.attr
                self_attr = isinstance(t.value, ast.Name)
            elif isinstance(t, ast.Name):
                target_attr = t.id
        if target_attr is None:
            return None, ""
        cur: Optional[ast.AST] = node
        while cur is not None:
            cur = getattr(cur, "_orion_parent", None)
            if isinstance(cur, ast.ClassDef):
                return target_attr, cur.name
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self_attr:
                    grand = getattr(cur, "_orion_parent", None)
                    if isinstance(grand, ast.ClassDef):
                        return target_attr, grand.name
                return target_attr, cur.name
        return target_attr, ""


class LockScopeCreepRule(_TierDRule):
    id = RULE_CREEP
    title = "strict-scope lock held across an unknown call"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if self._skip(ctx):
            return
        model = _model(ctx, self.table)
        for info, call, held in model.iter_calls():
            strict = [
                lock for lock in sorted(held)
                if self.table.decl(lock).strict_scope
            ]
            if not strict:
                continue
            label = self._unknown(model, info, call, strict)
            if label is None:
                continue
            locks = ", ".join(f"`{lock}`" for lock in strict)
            yield Finding(
                self.id, ctx.path, call.lineno,
                f"{label} while holding {locks}: the lock is declared "
                "strict-scope (bookkeeping only) and the auditor has no "
                "summary for this call — move it outside the lock, or "
                "declare it in allow_calls with a rationale",
            )

    def _unknown(self, model: ConcurrencyModel, info: _FnInfo,
                 call: ast.Call, strict: List[str]) -> Optional[str]:
        """A display label when the call is unknown code, else None."""
        allow: Set[str] = set()
        for lock in strict:
            allow.update(self.table.decl(lock).allow_calls)
        name = dotted_name(call.func)
        if isinstance(call.func, ast.Name):
            fn = call.func.id
            if (fn in _BUILTIN_NAMES or fn in allow
                    or (fn[:1].isupper())  # CapWords: a constructor
                    or model.is_same_module_callee(info, call)):
                return None
            return f"call to `{fn}`"
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr in _DATA_METHODS or attr in allow:
                return None
            if name and (name in _SAFE_DOTTED or name in allow):
                return None
            parts = _receiver_parts(call.func)
            if parts and parts[0] == "self" and len(parts) == 2:
                if attr in _SAFE_SELF_ATTRS:
                    return None
                if model.is_same_module_callee(info, call):
                    return None
                return f"call to stored callable `self.{attr}`"
            if attr in ("acquire", "release", "locked", "wait", "wait_for",
                        "notify", "notify_all"):
                # ops on a mapped lock/condition are the lock's own
                # protocol, not foreign code
                if self.map_lock(call, info, model) is not None:
                    return None
            return f"call to `{name or '.' + attr}`"
        return f"call to `{ast.dump(call.func)[:40]}`"

    @staticmethod
    def map_lock(call: ast.Call, info: _FnInfo,
                 model: ConcurrencyModel) -> Optional[str]:
        if isinstance(call.func, ast.Attribute):
            return model.map_lock(call.func.value, info.scope)
        return None


def concurrency_rules(table: Optional[LockTable] = None) -> List:
    return [
        LockOrderInversionRule(table),
        BlockingUnderLockRule(table),
        UnguardedSharedFieldRule(table),
        UndeclaredLockRule(table),
        LockScopeCreepRule(table),
    ]


# -- tier entry points ---------------------------------------------------------


def audit_concurrency(
    paths=None,
    root: str = "",
    baseline: Tuple[BaselineEntry, ...] = (),
    keep_suppressed: bool = False,
    table: Optional[LockTable] = None,
) -> List[Finding]:
    """Run Tier D over the four threaded packages (or explicit paths)."""
    if not root:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    if paths is None:
        paths = [os.path.join(root, p) for p in TIER_D_PACKAGES]
    return lint_paths(
        paths, rules=concurrency_rules(table), baseline=baseline,
        root=root, keep_suppressed=keep_suppressed,
    )


def audit_source(source: str, path: str,
                 table: Optional[LockTable] = None) -> List[Finding]:
    """Tier D over one in-memory module (the test fixture entry point)."""
    from orion_tpu.analysis.lint import lint_source

    return lint_source(source, path, rules=concurrency_rules(table))


__all__ = [
    "ALL_CONCURRENCY_CHECKS", "ConcurrencyModel", "LockTable",
    "audit_concurrency", "audit_source", "concurrency_rules",
    "load_lock_table",
    "RULE_ORDER", "RULE_BLOCKING", "RULE_UNGUARDED", "RULE_UNDECLARED",
    "RULE_CREEP",
]
