"""Tier B: jaxpr contract auditor — trace, never execute.

The runtime invariants the paper's headline claims rest on are properties of
the *traced program*, not of any particular run, so they are asserted on
jaxprs obtained with ``jax.make_jaxpr`` over abstract shapes (no params are
materialized, nothing runs on device):

- ``decode-no-collectives`` — the recurrent decode jaxpr contains no
  collective primitives: the O(1)-state decode path must stay
  communication-free (collectives leaking in via sharding rules would
  serialize every generated token on the slowest link).
- ``decode-o1-state``     — the decode scan's carry is byte-identical when
  the prompt length and the number of generated tokens change: per-token
  state is O(1) in sequence length, the paper's headline claim.
- ``bf16-matmul-policy``  — every ``dot_general`` in the bf16 train step
  consumes bf16 inputs, except matmuls whose source scope is declared in
  ``models/configs.py::F32_MATMUL_SCOPES`` (the fp32 kv-state accumulation
  contract). A silent f32 upcast halves MXU throughput and doubles HBM
  traffic without failing any parity test.
- ``no-host-callback``    — no callback/infeed/outfeed primitives inside the
  jitted step bodies: a host round-trip inside the decode scan or the train
  step serializes the device pipeline.

``audit_repo()`` traces the three contract-bearing entrypoints — the jitted
LM train step, the LRA train step, and the recurrent decode step — and
returns findings; the CLI runs it as tier B. The per-contract functions take
explicit jaxprs so tests can feed deliberately-broken toy functions.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

from orion_tpu.analysis.findings import Finding, normalize_path

CONTRACT_DECODE_COLLECTIVES = "decode-no-collectives"
CONTRACT_DECODE_STATE = "decode-o1-state"
CONTRACT_BF16_MATMUL = "bf16-matmul-policy"
CONTRACT_HOST_CALLBACK = "no-host-callback"
AUDIT_ERROR = "audit-error"

ALL_CONTRACTS = (
    CONTRACT_DECODE_COLLECTIVES,
    CONTRACT_DECODE_STATE,
    CONTRACT_BF16_MATMUL,
    CONTRACT_HOST_CALLBACK,
)

COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "all_gather", "all_to_all", "ppermute", "pmax", "pmin",
    "reduce_scatter", "psum_scatter", "pgather", "pbroadcast", "axis_index",
})

HOST_CALLBACK_PRIMS = frozenset({
    "debug_callback", "pure_callback", "io_callback", "callback",
    "outside_call", "infeed", "outfeed",
})


# -- jaxpr walking ------------------------------------------------------------


def iter_eqns(jaxpr) -> Iterator[Any]:
    """Every eqn in ``jaxpr`` and, recursively, in sub-jaxprs carried in eqn
    params (pjit/scan/while/cond/custom_vjp bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in v if isinstance(v, (list, tuple)) else (v,):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None:  # ClosedJaxpr
                    yield from iter_eqns(inner)
                elif hasattr(sub, "eqns"):  # raw Jaxpr
                    yield from iter_eqns(sub)


def _user_frames(eqn) -> List[Any]:
    try:
        from jax._src import source_info_util

        return list(source_info_util.user_frames(eqn.source_info))
    except Exception:
        return []


def _repo_root() -> str:
    import orion_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(orion_tpu.__file__)))


def _where(eqn, target: str) -> Tuple[str, int]:
    for fr in _user_frames(eqn):
        fname = getattr(fr, "file_name", "") or ""
        line = getattr(fr, "start_line", None) or getattr(fr, "line_num", 0)
        if fname:
            # repo-relative like Tier A findings, so baseline.json entries
            # match on any checkout
            return normalize_path(fname, _repo_root()), int(line or 0)
    return f"<jaxpr:{target}>", 0


def _scope_names(eqn) -> List[str]:
    """'file.py' and 'file.py::function' labels for every user frame."""
    out = []
    for fr in _user_frames(eqn):
        base = (getattr(fr, "file_name", "") or "").rsplit("/", 1)[-1]
        fn = getattr(fr, "function_name", "") or ""
        out.extend((base, f"{base}::{fn}"))
    return out


def _largest_scan(jaxpr):
    scans = [e for e in iter_eqns(jaxpr) if e.primitive.name == "scan"]
    if not scans:
        return None
    return max(scans, key=lambda e: e.params.get("length") or 0)


def scan_carry_avals(jaxpr) -> Optional[Tuple[Tuple[Any, str], ...]]:
    """(shape, dtype) of each carry of the longest scan, or None if no scan."""
    eqn = _largest_scan(jaxpr)
    if eqn is None:
        return None
    n_const, n_carry = eqn.params["num_consts"], eqn.params["num_carry"]
    carries = eqn.invars[n_const:n_const + n_carry]
    return tuple(
        (tuple(v.aval.shape), str(v.aval.dtype)) for v in carries
    )


# -- contracts ----------------------------------------------------------------


def audit_no_collectives(closed_jaxpr, target: str) -> List[Finding]:
    out = []
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            path, line = _where(eqn, target)
            out.append(Finding(
                CONTRACT_DECODE_COLLECTIVES, path, line,
                f"collective `{eqn.primitive.name}` in the {target} jaxpr: "
                "the recurrent decode path must stay communication-free",
            ))
    return out


def audit_no_host_callbacks(closed_jaxpr, target: str) -> List[Finding]:
    out = []
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name in HOST_CALLBACK_PRIMS:
            path, line = _where(eqn, target)
            out.append(Finding(
                CONTRACT_HOST_CALLBACK, path, line,
                f"host callback `{eqn.primitive.name}` in the {target} "
                "jaxpr: host round-trips serialize the device pipeline",
            ))
    return out


def audit_matmul_bf16(
    closed_jaxpr, target: str, allowed_scopes: Sequence[str] = ()
) -> List[Finding]:
    """Flag dot_generals whose inputs are all float32 (a silent upcast in a
    bf16-policy step) unless a source frame matches ``allowed_scopes``."""
    out = []
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        dtypes = {str(v.aval.dtype) for v in eqn.invars}
        if dtypes != {"float32"}:
            continue  # bf16 inputs (f32 accumulation via preferred dtype ok)
        scopes = _scope_names(eqn)
        if any(s in scopes for s in allowed_scopes):
            continue
        path, line = _where(eqn, target)
        fn = scopes[1] if len(scopes) > 1 else "<unknown scope>"
        out.append(Finding(
            CONTRACT_BF16_MATMUL, path, line,
            f"f32xf32 dot_general from {fn} in the bf16 {target} step; "
            "declare the scope in models/configs.py::F32_MATMUL_SCOPES if "
            "the fp32 accumulation is intentional",
        ))
    return out


def audit_scan_state_invariance(
    jaxprs_by_size: Sequence[Tuple[str, Any]], target: str
) -> List[Finding]:
    """``jaxprs_by_size``: (label, closed_jaxpr) traced at different
    sequence/step counts. The decode scan's carry must be identical across
    all of them — O(1) state per token."""
    carries = []
    for label, jx in jaxprs_by_size:
        c = scan_carry_avals(jx.jaxpr)
        if c is None:
            return [Finding(
                CONTRACT_DECODE_STATE, f"<jaxpr:{target}>", 0,
                f"no scan found in the {target} jaxpr traced at {label}: "
                "the decode loop is expected to be ONE lax.scan",
            )]
        carries.append((label, c))
    ref_label, ref = carries[0]
    out = []
    for label, c in carries[1:]:
        if c != ref:
            out.append(Finding(
                CONTRACT_DECODE_STATE, f"<jaxpr:{target}>", 0,
                f"decode scan carry changes with sequence length "
                f"({ref_label}: {ref} != {label}: {c}): the O(1)-state "
                "contract is broken — some per-layer state grows with T",
            ))
    return out


# -- repo targets -------------------------------------------------------------


def trace_decode(prompt_len: int, max_new_tokens: int, cfg_name: str = "tiny"):
    """Abstractly trace the jitted recurrent decode entrypoint."""
    import jax
    import jax.numpy as jnp

    from orion_tpu.generate import SampleConfig, _generate_jit
    from orion_tpu.models.configs import get_config
    from orion_tpu.models.transformer import TransformerLM

    model = TransformerLM(get_config(cfg_name))
    key = jax.random.PRNGKey(0)
    prompt = jax.ShapeDtypeStruct((1, prompt_len), jnp.int32)
    params = jax.eval_shape(model.init, key, prompt)
    return jax.make_jaxpr(_generate_jit, static_argnums=(0, 3, 4))(
        model, params, prompt, max_new_tokens, SampleConfig(), key
    )


def trace_train_step(dtype: str = "bfloat16", seq_len: int = 32):
    """Abstractly trace the Trainer's jitted step body on a bf16 tiny
    config (materialize=False: shapes only, no weights allocated)."""
    import jax
    import jax.numpy as jnp

    from orion_tpu.models.configs import get_config
    from orion_tpu.training.trainer import TrainConfig, Trainer

    cfg = TrainConfig(
        model=dataclasses.replace(get_config("tiny"), dtype=dtype),
        batch_size=2, seq_len=seq_len, steps=10,
    )
    tr = Trainer(cfg, materialize=False)
    batch = jax.ShapeDtypeStruct((cfg.batch_size, cfg.seq_len + 1), jnp.int32)
    return jax.make_jaxpr(tr._train_step)(tr._abstract, batch)


def trace_lra_step(cfg_name: str = "lra_listops_linear", seq_len: int = 64):
    """Abstractly trace the LRA classification train step."""
    import jax
    import jax.numpy as jnp

    from orion_tpu.models.classifier import LRAClassifier
    from orion_tpu.models.configs import get_config
    from orion_tpu.train_lra import make_lra_step
    from orion_tpu.training import trainer as tr
    from orion_tpu.utils import rng as rngs

    mcfg = get_config(cfg_name)
    model = LRAClassifier(mcfg)
    shim = tr.TrainConfig(model=mcfg, steps=10)
    tx = tr.make_optimizer(shim)
    sched = tr.make_schedule(shim)
    root = rngs.root_key(0)
    step_fn, _ = make_lra_step(model, tx, sched, root, mcfg.dropout)

    key = jax.random.PRNGKey(0)
    toks = jax.ShapeDtypeStruct((2, seq_len), jnp.int32)
    mask = jax.ShapeDtypeStruct((2, seq_len), jnp.bool_)
    labels = jax.ShapeDtypeStruct((2,), jnp.int32)
    params = jax.eval_shape(model.init, key, toks, mask)
    state = jax.eval_shape(
        lambda p: {
            "params": p, "opt": tx.init(p),
            "step": jnp.zeros((), jnp.int32),
        },
        params,
    )
    return jax.make_jaxpr(step_fn)(state, toks, labels, mask)


def _f32_scopes() -> Tuple[str, ...]:
    from orion_tpu.models.configs import F32_MATMUL_SCOPES

    return F32_MATMUL_SCOPES


def _audit_target(
    name: str, fn: Callable[[], List[Finding]], findings: List[Finding]
) -> None:
    try:
        findings.extend(fn())
    except Exception as e:  # noqa: BLE001 - surfaced as a finding, not a crash
        findings.append(Finding(
            AUDIT_ERROR, f"<jaxpr:{name}>", 0,
            f"tracing {name} failed: {type(e).__name__}: {e}",
        ))


def audit_repo() -> List[Finding]:
    """Trace the three contract-bearing entrypoints and run every contract."""
    findings: List[Finding] = []

    def decode() -> List[Finding]:
        jx_small = trace_decode(8, 8)
        jx_large = trace_decode(16, 16)
        out = audit_no_collectives(jx_small, "decode")
        out += audit_no_host_callbacks(jx_small, "decode")
        out += audit_scan_state_invariance(
            [("t0=8,n=8", jx_small), ("t0=16,n=16", jx_large)], "decode"
        )
        return out

    def train() -> List[Finding]:
        jx = trace_train_step()
        out = audit_matmul_bf16(jx, "train", allowed_scopes=_f32_scopes())
        out += audit_no_host_callbacks(jx, "train")
        return out

    def lra() -> List[Finding]:
        jx = trace_lra_step()
        return audit_no_host_callbacks(jx, "lra")

    _audit_target("decode", decode, findings)
    _audit_target("train", train, findings)
    _audit_target("lra", lra, findings)
    return findings


__all__ = [
    "audit_repo", "audit_no_collectives", "audit_no_host_callbacks",
    "audit_matmul_bf16", "audit_scan_state_invariance", "iter_eqns",
    "scan_carry_avals", "trace_decode", "trace_train_step", "trace_lra_step",
    "ALL_CONTRACTS", "CONTRACT_DECODE_COLLECTIVES", "CONTRACT_DECODE_STATE",
    "CONTRACT_BF16_MATMUL", "CONTRACT_HOST_CALLBACK", "AUDIT_ERROR",
]
