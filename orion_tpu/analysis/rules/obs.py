"""Telemetry-layer rule.

``obs-device-sync`` — the telemetry spine's hard constraint is that no
instrumentation point may add a device host-sync or a new compile: every
interesting serving event already happens at a chunk boundary on the
host thread (the O(1)-state dividend), so metrics/trace/flight code must
be PURE host code. Two scopes enforce that:

1. **the obs package** (``orion_tpu/obs/``): importing jax/jaxlib at
   all, any ``jax.*``/``jnp.*`` dotted call, ``.block_until_ready()`` /
   ``.item()``, ``float()``/``int()`` calls (the classic
   concretize-a-device-scalar syncs — obs code must receive host
   numbers, never coerce), and ``np.asarray``/``jax.device_get`` are all
   findings. A device array should not even be REACHABLE from obs code;
   banning the jax import makes ``__getitem__``-style syncs structurally
   impossible rather than pattern-matched.

2. **registered hooks** (any ``orion_tpu/`` module): a function handed
   to the spine as a callback — ``gauge_fn(...)`` callables, inject
   ``add_observer`` subscribers, ``attach_inject`` targets, callables
   bound to the hook keywords ``on_event`` / ``on_transition`` /
   ``on_done`` / ``on_stop`` / ``observer``, (since ISSUE 10) the
   live-endpoint provider keywords ``metrics_fn`` / ``health_fn`` /
   ``statusz_fn`` / ``slo_fn`` (obs/http.py handlers call them from
   scrape threads), and (since ISSUE 15) the cost surfaces — the
   ``costz_fn`` / ``profilez_fn`` endpoint providers, ``cost_fn`` /
   ``capacity_fn`` callbacks, and any ``*_cost``-named function passed
   as a callback argument to ANY call (a cost provider by naming
   contract, wherever it gets registered) — runs on the scheduler's
   hot path (chunk
   boundaries, signal delivery, metric scrapes). Inside such functions
   (named functions resolved same-module, plus inline lambdas), the
   sync-shaped calls above and any ``jax.``/``jnp.`` dotted call are
   findings.

The ``decode-host-sync`` probe budget is untouched: that rule gates the
decode LOOPS; this one gates the telemetry layer those loops report
into. Together they pin the acceptance criterion "zero per-chunk host
syncs with telemetry fully on" statically.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from orion_tpu.analysis.findings import Finding
from orion_tpu.analysis.lint import ModuleContext, dotted_name

_SYNC_ATTRS = frozenset({"block_until_ready", "item"})
_SYNC_NAMES = frozenset({"float", "int"})
_SYNC_DOTTED = frozenset({
    "np.asarray", "numpy.asarray", "onp.asarray", "jax.device_get",
})
_BANNED_IMPORT_ROOTS = ("jax", "jaxlib")
# call names whose function-valued arguments become spine hooks
_HOOK_CALL_NAMES = frozenset({"gauge_fn", "add_observer", "attach_inject"})
_HOOK_KEYWORDS = frozenset({
    "on_event", "on_transition", "on_done", "on_stop", "observer",
    "on_stall",
    # obs/http.py provider registration: these callables run on the
    # endpoint's scrape-handler threads — a /metrics or /healthz GET
    # must never sync a device value, so every registered provider is
    # in the banned-sync scope wherever it is defined
    "metrics_fn", "health_fn", "statusz_fn", "slo_fn",
    # ISSUE 15 cost/capacity surfaces: the /costz and /profilez
    # providers plus any cost/capacity callback handed to the spine —
    # cost accounting runs once per chunk boundary and per scrape, the
    # two hottest host paths there are
    "costz_fn", "profilez_fn", "cost_fn", "capacity_fn",
})


def _is_obs_module(path: str) -> bool:
    return "orion_tpu/obs/" in path or path.startswith("obs/")


def _sync_label(node: ast.Call) -> Optional[str]:
    """Is this call sync-shaped, and how do we print it? (Superset of
    decode-host-sync's set: int() concretizes a device scalar exactly
    like float() does.)"""
    name = dotted_name(node.func)
    if name in _SYNC_NAMES or name in _SYNC_DOTTED:
        return f"{name}()"
    if name is not None and (
        name.startswith("jax.") or name.startswith("jnp.")
    ):
        return f"{name}()"
    if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_ATTRS:
        return f".{node.func.attr}()"
    return None


def _hook_functions(ctx: ModuleContext) -> List[ast.AST]:
    """Function defs (and lambdas) registered as metric/trace/flight
    hooks: passed positionally to gauge_fn/add_observer, or bound to a
    hook keyword anywhere in the module. Named references resolve to
    same-module defs; ``self._method`` references resolve by attribute
    name."""
    by_name = {}
    for fn in ctx.function_defs:
        by_name.setdefault(fn.name, []).append(fn)
    hooks: List[ast.AST] = []
    seen: Set[int] = set()

    def claim(expr: ast.AST) -> None:
        if isinstance(expr, ast.Lambda):
            if id(expr) not in seen:
                seen.add(id(expr))
                hooks.append(expr)
            return
        name = dotted_name(expr)
        if not name:
            return
        for fn in by_name.get(name.rsplit(".", 1)[-1], []):
            if id(fn) not in seen:
                seen.add(id(fn))
                hooks.append(fn)

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            leaf = callee.rsplit(".", 1)[-1] if callee else ""
            if leaf in _HOOK_CALL_NAMES:
                for arg in node.args:
                    claim(arg)
            for kw in node.keywords:
                if kw.arg in _HOOK_KEYWORDS:
                    claim(kw.value)
            # a *_cost-named function passed as a callback ANYWHERE is a
            # cost provider by naming contract (ISSUE 15): whatever call
            # registers it — a spine keyword we enumerated or a future
            # registrar we didn't — its body is banned-sync scope
            for expr in list(node.args) + [kw.value for kw in node.keywords]:
                name = dotted_name(expr)
                if name and name.rsplit(".", 1)[-1].endswith("_cost"):
                    claim(expr)
        elif isinstance(node, ast.Assign):
            # `pending.on_done = fn` — hook registration by assignment
            for target in node.targets:
                if (isinstance(target, ast.Attribute)
                        and target.attr in _HOOK_KEYWORDS):
                    claim(node.value)
    return hooks


class ObsDeviceSyncRule:
    id = "obs-device-sync"
    title = "device sync / jax usage in the telemetry layer"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.is_test:
            return
        in_obs = _is_obs_module(ctx.path)
        if in_obs:
            yield from self._check_obs_module(ctx)
        if not ctx.path.startswith("orion_tpu/") and not in_obs:
            return
        yield from self._check_hooks(ctx)

    def _check_obs_module(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    root = alias.name.split(".", 1)[0]
                    if root in _BANNED_IMPORT_ROOTS:
                        yield Finding(
                            self.id, ctx.path, node.lineno,
                            f"import {alias.name} inside orion_tpu/obs/: "
                            "the telemetry spine is host-only by contract "
                            "— a device value must be concretized at the "
                            "chunk boundary that produced it, never "
                            "inside a metric/trace/flight path",
                        )
            elif isinstance(node, ast.ImportFrom):
                root = (node.module or "").split(".", 1)[0]
                if root in _BANNED_IMPORT_ROOTS:
                    yield Finding(
                        self.id, ctx.path, node.lineno,
                        f"from {node.module} import ... inside "
                        "orion_tpu/obs/: the telemetry spine is host-only "
                        "by contract (see module docstring)",
                    )
            elif isinstance(node, ast.Call):
                sync = _sync_label(node)
                if sync is not None:
                    yield Finding(
                        self.id, ctx.path, node.lineno,
                        f"{sync} inside orion_tpu/obs/: telemetry code "
                        "must receive host numbers, never concretize or "
                        "sync — pass plain ints/floats in from the chunk "
                        "boundary that already holds them",
                    )

    def _check_hooks(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _hook_functions(ctx):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                sync = _sync_label(node)
                if sync is not None:
                    yield Finding(
                        self.id, ctx.path, node.lineno,
                        f"{sync} inside a function registered as a "
                        "metric/trace/flight hook: hooks run on the "
                        "scheduler's chunk-boundary hot path (or in "
                        "signal context) — a device sync there stalls "
                        "every resident slot once per chunk; record the "
                        "host mirror instead",
                    )


RULES = [ObsDeviceSyncRule()]
