"""Performance-contract rules.

``loop-accum``      — no Python-loop jnp accumulation in hot paths (trainer /
                      generate / ops): a ``for`` that grows or re-binds an
                      array with jnp calls unrolls into O(steps) HLO — the
                      recompile-per-length, no-fusion anti-pattern the scan
                      forms exist to avoid.
``float64-literal`` — no float64 dtypes outside tests: TPUs have no f64
                      units (everything silently demotes or dies), and on
                      CPU parity paths a stray f64 doubles memory and hides
                      bf16 numerics bugs the tests exist to catch.
"""

from __future__ import annotations

import ast
from typing import Iterator

from orion_tpu.analysis.findings import Finding
from orion_tpu.analysis.lint import ModuleContext, dotted_name

_JNP_PREFIXES = ("jnp.", "jax.numpy.")
_CONCAT_CALLS = {
    "jnp.concatenate", "jnp.append", "jnp.stack", "jnp.vstack",
    "jnp.hstack", "jax.numpy.concatenate", "jax.numpy.append",
    "jax.numpy.stack",
}
_F64_ATTRS = {
    "jnp.float64", "np.float64", "numpy.float64", "jax.numpy.float64",
}


def _contains_jnp_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = dotted_name(sub.func)
            if name and name.startswith(_JNP_PREFIXES):
                return True
    return False


def _names_in(node: ast.AST):
    return {
        sub.id for sub in ast.walk(node) if isinstance(sub, ast.Name)
    }


class LoopAccumRule:
    id = "loop-accum"
    title = "Python-loop jnp accumulation in a hot path"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.is_hot_path or ctx.is_test:
            return
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if isinstance(node, ast.AugAssign) and _contains_jnp_call(
                    node.value
                ):
                    yield Finding(
                        self.id, ctx.path, node.lineno,
                        "jnp accumulation via augmented assignment inside a "
                        "Python loop: unrolled O(steps) HLO — use "
                        "jax.lax.scan / fori_loop",
                    )
                elif isinstance(node, ast.Assign):
                    if len(node.targets) != 1 or not isinstance(
                        node.targets[0], ast.Name
                    ):
                        continue
                    target = node.targets[0].id
                    if not isinstance(node.value, ast.Call):
                        continue
                    if (
                        dotted_name(node.value.func) in _CONCAT_CALLS
                        and target in _names_in(node.value)
                    ):
                        yield Finding(
                            self.id, ctx.path, node.lineno,
                            f"growing {target!r} with "
                            f"{dotted_name(node.value.func)} inside a "
                            "Python loop: O(steps^2) copies and O(steps) "
                            "HLO — carry a preallocated buffer through "
                            "lax.scan instead",
                        )


class Float64Rule:
    id = "float64-literal"
    title = "float64 dtype outside tests"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.is_test:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                name = dotted_name(node)
                if name in _F64_ATTRS:
                    yield Finding(
                        self.id, ctx.path, node.lineno,
                        f"{name}: TPUs have no f64 — this silently demotes "
                        "or doubles memory on parity paths; use float32",
                    )
            # the comparison constant below is this rule's own probe, not a
            # dtype use — the one legitimate in-repo suppression
            elif (
                isinstance(node, ast.Constant)
                and node.value == "float64"  # orion: noqa[float64-literal]
            ):
                yield Finding(
                    self.id, ctx.path, node.lineno,
                    "'float64' dtype string outside tests; use 'float32'",
                )
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if (
                    name in ("jax.config.update", "config.update")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value == "jax_enable_x64"
                ):
                    yield Finding(
                        self.id, ctx.path, node.lineno,
                        "jax_enable_x64 flips global default dtypes — "
                        "never in library code",
                    )


RULES = [LoopAccumRule(), Float64Rule()]
