"""General Python-hygiene rules with JAX-specific failure modes.

``mutable-default`` — a mutable default argument is shared across calls; in
                      this codebase the sharper hazard is a default that
                      later flows into a jit static arg or a config pytree,
                      where aliasing means cross-call state leaks.
``bare-except``     — ``except:`` swallows ``KeyboardInterrupt`` and —
                      worse here — XLA's ``RESOURCE_EXHAUSTED`` / Mosaic
                      compile errors that callers (e.g. the trainer's OOM
                      remat fallback) dispatch on by type and message.
"""

from __future__ import annotations

import ast
from typing import Iterator

from orion_tpu.analysis.findings import Finding
from orion_tpu.analysis.lint import ModuleContext, dotted_name


class MutableDefaultRule:
    id = "mutable-default"
    title = "mutable default argument"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in ctx.function_defs:
            args = fn.args
            for d in list(args.defaults) + [
                kd for kd in args.kw_defaults if kd is not None
            ]:
                if isinstance(
                    d,
                    (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                     ast.SetComp),
                ) or (
                    isinstance(d, ast.Call)
                    and dotted_name(d.func) in ("list", "dict", "set")
                ):
                    yield Finding(
                        self.id, ctx.path, d.lineno,
                        f"mutable default in {fn.name}(): shared across "
                        "calls — default to None and construct inside",
                    )


class BareExceptRule:
    id = "bare-except"
    title = "bare except clause"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield Finding(
                    self.id, ctx.path, node.lineno,
                    "bare except: catches KeyboardInterrupt and masks XLA "
                    "compile/OOM errors callers dispatch on — name the "
                    "exception type",
                )


RULES = [MutableDefaultRule(), BareExceptRule()]
