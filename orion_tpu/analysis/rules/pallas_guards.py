"""``pallas-chunk-guard`` — every public Pallas entrypoint must handle
indivisible shapes explicitly.

Mosaic kernels tile the token axis by a chunk/block size; a shape that does
not divide it either miscompiles (garbage in the ragged tail) or fails deep
inside Mosaic with an error no caller can act on. The repo-wide idiom
(ops/pallas/causal_dot.py, flash_attention.py, gmm.py) is to either pad —
``rem = (-t) % chunk`` — or assert divisibility — ``assert m % tile_rows ==
0`` — before the ``pl.pallas_call``. This rule enforces that every *public*
function in ``ops/pallas/`` that (transitively, within the module) reaches a
``pallas_call`` has a ``%`` guard somewhere on that intra-module path.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from orion_tpu.analysis.findings import Finding
from orion_tpu.analysis.lint import ModuleContext, dotted_name


def _module_functions(ctx: ModuleContext) -> Dict[str, ast.AST]:
    """Module-level (top-of-file) function defs by name."""
    return {
        n.name: n
        for n in ctx.tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _calls_pallas(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and name.rsplit(".", 1)[-1] == "pallas_call":
                return True
    return False


def _has_mod_guard(fn: ast.AST) -> bool:
    """A ``%`` expression (padding arithmetic or a divisibility assert) or
    an explicit check helper call anywhere in the function body."""
    for node in ast.walk(fn):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
            return True
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            leaf = name.rsplit(".", 1)[-1]
            if "divis" in leaf or leaf.startswith("check_"):
                return True
    return False


def _callees(fn: ast.AST, fns: Dict[str, ast.AST]) -> List[ast.AST]:
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and name in fns:
                out.append(fns[name])
    return out


class PallasChunkGuardRule:
    id = "pallas-chunk-guard"
    title = "public pallas entrypoint without a chunk-divisibility guard"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.is_pallas_module:
            return
        fns = _module_functions(ctx)

        def reach(fn: ast.AST, seen: Set[int]):
            """All module fns on fn's intra-module call graph, incl. fn."""
            if id(fn) in seen:
                return
            seen.add(id(fn))
            yield fn
            for g in _callees(fn, fns):
                yield from reach(g, seen)

        for name, fn in fns.items():
            if name.startswith("_"):
                continue
            reachable = list(reach(fn, set()))
            if not any(_calls_pallas(g) for g in reachable):
                continue
            if not any(_has_mod_guard(g) for g in reachable):
                yield Finding(
                    self.id, ctx.path, fn.lineno,
                    f"{name}() reaches a pallas_call with no "
                    "chunk/block-divisibility guard or padding on the path "
                    "— pad with `(-t) % chunk` or assert divisibility "
                    "before launching the kernel",
                )


RULES = [PallasChunkGuardRule()]
