"""Rules about what may appear inside jit-traced code.

``jit-debug``     — no ``print``/``jax.debug.*`` inside traced scopes: a
                    ``print`` fires at trace time (once, with tracers), and
                    ``jax.debug.print``/``callback`` insert host round-trips
                    that serialize the decode loop.
``tracer-host``   — no ``.item()``/``.tolist()``/``float()``/``int()``/
                    ``np.asarray()`` on values inside traced scopes: these
                    force a host-device sync (or fail outright under jit).
``static-hashable`` — parameters named by ``static_argnums``/
                    ``static_argnames`` must be hashable-typed; an unhashable
                    static arg either crashes at call time or — worse, for
                    types with identity hashing — silently recompiles on
                    every call.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from orion_tpu.analysis.findings import Finding
from orion_tpu.analysis.lint import (
    ModuleContext,
    dotted_name,
    jit_decorations,
)

_DEBUG_CALLS = {
    "print",
    "jax.debug.print",
    "jax.debug.callback",
    "jax.debug.breakpoint",
    "debug.print",
    "debug.callback",
    "debug.breakpoint",
}

_HOST_NP_CALLS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array",
}

_UNHASHABLE_TYPE_NAMES = {"list", "dict", "set", "List", "Dict", "Set",
                          "bytearray"}


class JitDebugRule:
    id = "jit-debug"
    title = "print/jax.debug.* inside a traced function"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in _DEBUG_CALLS and ctx.in_traced_scope(node):
                yield Finding(
                    self.id, ctx.path, node.lineno,
                    f"{name}() inside a jit-traced function: trace-time "
                    "side effect / host round-trip in the compiled path",
                )


class TracerHostRule:
    id = "tracer-host"
    title = "host materialization of a tracer"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not ctx.in_traced_scope(node):
                continue
            name = dotted_name(node.func)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("item", "tolist")
                and not node.args
            ):
                yield Finding(
                    self.id, ctx.path, node.lineno,
                    f".{node.func.attr}() in a traced scope forces a "
                    "host-device sync (ConcretizationTypeError under jit)",
                )
            elif name in ("float", "int", "bool") and len(node.args) == 1:
                if not isinstance(node.args[0], ast.Constant):
                    yield Finding(
                        self.id, ctx.path, node.lineno,
                        f"{name}() on a traced value concretizes the "
                        "tracer; use jnp casts/astype instead",
                    )
            elif name in _HOST_NP_CALLS:
                yield Finding(
                    self.id, ctx.path, node.lineno,
                    f"{name}() in a traced scope pulls the tracer to host "
                    "numpy; use jnp.asarray",
                )


def _literal_ints(node: ast.AST) -> Optional[List[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[int] = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return out
    return None


def _literal_strs(node: ast.AST) -> Optional[List[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return out
    return None


def _annotation_unhashable(node: Optional[ast.AST]) -> bool:
    if node is None:
        return False
    if isinstance(node, ast.Subscript):
        node = node.value
    name = dotted_name(node)
    return bool(name) and name.rsplit(".", 1)[-1] in _UNHASHABLE_TYPE_NAMES


def _default_unhashable(node: Optional[ast.AST]) -> bool:
    if node is None:
        return False
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return dotted_name(node.func) in ("list", "dict", "set")
    return False


class StaticHashableRule:
    id = "static-hashable"
    title = "static_argnums/static_argnames must name hashable-typed params"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in ctx.function_defs:
            for deco in jit_decorations(fn):
                if not isinstance(deco, ast.Call):
                    continue
                yield from self._check_decoration(ctx, fn, deco)

    def _static_params(
        self, fn, deco: ast.Call
    ) -> Tuple[List[ast.arg], List[Tuple[int, str]], int]:
        """Resolve the params a jit decoration marks static; ``bad`` holds
        (lineno, kwarg-name) for non-literal static specs."""
        args = fn.args
        pos: List[ast.arg] = list(args.posonlyargs) + list(args.args)
        params: List[ast.arg] = []
        bad: List[Tuple[int, str]] = []
        for kw in deco.keywords:
            if kw.arg == "static_argnums":
                nums = _literal_ints(kw.value)
                if nums is None:
                    bad.append((kw.value.lineno, "static_argnums"))
                    continue
                for i in nums:
                    if 0 <= i < len(pos):
                        params.append(pos[i])
            elif kw.arg == "static_argnames":
                names = _literal_strs(kw.value)
                if names is None:
                    bad.append((kw.value.lineno, "static_argnames"))
                    continue
                byname = {a.arg: a for a in pos + list(args.kwonlyargs)}
                params.extend(byname[n] for n in names if n in byname)
        return params, bad, len(pos)

    def _check_decoration(
        self, ctx: ModuleContext, fn, deco: ast.Call
    ) -> Iterator[Finding]:
        params, bad, n_pos = self._static_params(fn, deco)
        for lineno, which in bad:
            yield Finding(
                self.id, ctx.path, lineno,
                f"{which} on {fn.name}() is not a literal int/str/tuple: "
                "the static set cannot be audited (and non-literal specs "
                "invite unhashable surprises)",
            )
        # map param -> default expression (positional defaults are
        # right-aligned; kwonly defaults pair 1:1)
        args = fn.args
        pos = list(args.posonlyargs) + list(args.args)
        defaults = {}
        for a, d in zip(pos[n_pos - len(args.defaults):], args.defaults):
            defaults[a] = d
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            defaults[a] = d
        for p in params:
            if _annotation_unhashable(p.annotation):
                yield Finding(
                    self.id, ctx.path, p.lineno,
                    f"static param {p.arg!r} of {fn.name}() is annotated "
                    "with an unhashable type; jit static args are hashed "
                    "into the compilation cache key",
                )
            elif _default_unhashable(defaults.get(p)):
                yield Finding(
                    self.id, ctx.path, p.lineno,
                    f"static param {p.arg!r} of {fn.name}() defaults to an "
                    "unhashable value; calls without the arg will crash in "
                    "the jit cache lookup",
                )


RULES = [JitDebugRule(), TracerHostRule(), StaticHashableRule()]
