"""Concurrency-hygiene rules born from the resilience work.

``unbounded-wait`` — a ``queue.get()`` / ``Thread.join()`` with no timeout
                     blocks forever when the peer thread is dead or hung:
                     exactly the failure the watchdog/stall machinery
                     (resilience/watchdog.py) exists to convert into a
                     diagnosable ``StallError``. The data-loader hang this
                     rule encodes was real: a died prefetch worker left
                     ``__next__`` polling a queue that could never fill.

Heuristics (AST-only, no type info): a zero-argument ``.get()`` (or one
whose only kwarg is ``block``) can't be ``dict.get`` — that requires a key —
so it is a blocking queue read; a ``.join()`` with no arguments at all can't
be ``str.join``/``os.path.join`` — both require operands — so it is a
thread/process join. Calls carrying a ``timeout=`` kwarg pass. Test code is
exempt (tests may legitimately block on a result); real exceptions use the
standard ``# orion: noqa[unbounded-wait]`` / baseline escape hatch.
"""

from __future__ import annotations

import ast
from typing import Iterator

from orion_tpu.analysis.findings import Finding
from orion_tpu.analysis.lint import ModuleContext


class UnboundedWaitRule:
    id = "unbounded-wait"
    title = "unbounded blocking wait (no timeout)"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.is_test:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute
            ):
                continue
            meth = node.func.attr
            if meth not in ("get", "join"):
                continue
            if node.args:
                continue  # dict.get(key), "sep".join(parts), path.join(...)
            kws = {k.arg for k in node.keywords}
            if "timeout" in kws:
                continue
            if meth == "get" and kws - {"block"}:
                continue  # keyword'd non-queue .get()
            if meth == "join" and kws:
                continue
            yield Finding(
                self.id, ctx.path, node.lineno,
                f".{meth}() with no timeout blocks forever if the peer "
                "thread is dead or hung — pass timeout= and surface a "
                "StallError (resilience/watchdog.py), or suppress with "
                "# orion: noqa[unbounded-wait]",
            )


RULES = [UnboundedWaitRule()]
