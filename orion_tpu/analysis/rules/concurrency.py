"""Concurrency-hygiene rules born from the resilience work.

``unbounded-wait`` — a ``queue.get()`` / ``Thread.join()`` with no timeout
                     blocks forever when the peer thread is dead or hung:
                     exactly the failure the watchdog/stall machinery
                     (resilience/watchdog.py) exists to convert into a
                     diagnosable ``StallError``. The data-loader hang this
                     rule encodes was real: a died prefetch worker left
                     ``__next__`` polling a queue that could never fill.

Heuristics (AST-only, no type info): a zero-argument ``.get()`` (or one
whose only kwarg is ``block``) can't be ``dict.get`` — that requires a key —
so it is a blocking queue read; a ``.join()`` with no arguments at all can't
be ``str.join``/``os.path.join`` — both require operands — so it is a
thread/process join. Calls carrying a ``timeout=`` kwarg pass. Test code is
exempt (tests may legitimately block on a result); real exceptions use the
standard ``# orion: noqa[unbounded-wait]`` / baseline escape hatch.

In ``orion_tpu/fleet/`` the rule's method set widens to ``.wait()`` and
``.recv()``: there the peer of a wait is a child OS process (a replica)
that can be SIGKILLed or wedge in a C call — ``Popen.wait()``,
``Event.wait()``, and pipe ``recv()`` without timeouts park the
supervisor on a corpse, which is exactly the outcome the fleet's
heartbeat machinery exists to prevent.

In ``orion_tpu/obs/`` it widens further, to ``.wait()``/``.recv()``/
``.acquire()``: the spine's readers run on scrape-handler daemon
threads against locks the serving scheduler also holds, so an
unbounded block there couples the liveness of the /metrics endpoint to
the liveness of whatever wedged the scheduler — a scrape must return
or fail, never hang. (``with lock:`` is fine — obs locks are held for
one snapshot; it is the bare blocking ``acquire()`` call, which can
carry a timeout and doesn't, that the rule flags.) WHICH locks the
``.acquire()`` widening applies to is not this rule's call: the Tier D
declaration (serving/locks.py, via ``obs_lock_attrs()``) is the single
source of truth, so only an acquire on a receiver named like a
declared obs lock is in scope — an ``.acquire()`` on anything else is
not a spine lock and stays un-flagged, and a new obs lock enters this
rule's scope the moment it is declared, with no second list to update.

``signal-unsafe-handler`` — a Python signal handler runs between two
                     arbitrary bytecodes of whatever the main thread was
                     doing. Buffered I/O (``print``, ``open``,
                     ``.write``/``.flush``), lock acquisition, and
                     checkpoint saves inside the handler can re-enter a
                     lock the interrupted code already holds (logging and
                     io buffers lock internally) and deadlock exactly at
                     preemption time — the moment the resilience stack
                     most needs to work. Handlers must only set flags
                     (resilience/preempt.py: the trainer polls at step
                     boundaries, where the emergency checkpoint runs);
                     ``os.write`` is exempt — the unbuffered syscall is
                     the one async-signal-safe way to say something.

Detection: every function registered via ``signal.signal(sig, fn)`` (by
name or as a ``self.method`` attribute), closed over same-module calls —
a helper the handler calls is part of the handler.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from orion_tpu.analysis.findings import Finding
from orion_tpu.analysis.lint import ModuleContext, dotted_name


def _obs_lock_attrs():
    """Attribute names of the locks DECLARED in obs modules
    (serving/locks.py, the Tier D declaration) — the single source of
    truth for the obs ``.acquire()`` widening. Imported lazily to keep
    rule import free of the declaration loader."""
    from orion_tpu.analysis.concurrency_audit import load_locks_module

    return load_locks_module().obs_lock_attrs()


class UnboundedWaitRule:
    id = "unbounded-wait"
    title = "unbounded blocking wait (no timeout)"

    # in orion_tpu/fleet/ the peer of a wait is another OS PROCESS —
    # a child replica that can be SIGKILLed, OOM-killed, or wedged in a
    # C call at any time — so the method set widens: ``.wait()`` (process
    # wait / event wait) and ``.recv()`` (pipe read) without a timeout
    # park the parent forever on a corpse. Everywhere else those names
    # are too ambiguous to flag (a module-level ``wait`` helper, a
    # socket recv behind its own settimeout); the fleet's supervision
    # contract is precisely "every cross-process wait is bounded".
    _FLEET_METHODS = ("get", "join", "wait", "recv")
    # in orion_tpu/obs/ scrape-handler threads read state the scheduler
    # writes: a bare blocking ``.acquire()`` there welds the endpoint's
    # liveness to the scheduler's — add it to the widened set
    _OBS_METHODS = ("get", "join", "wait", "recv", "acquire")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.is_test:
            return
        if ctx.is_obs:
            methods = self._OBS_METHODS
        elif ctx.is_fleet:
            methods = self._FLEET_METHODS
        else:
            methods = ("get", "join")
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute
            ):
                continue
            meth = node.func.attr
            if meth not in methods:
                continue
            if node.args:
                continue  # dict.get(key), "sep".join(parts), wait(5.0), ...
            kws = {k.arg for k in node.keywords}
            if "timeout" in kws:
                continue
            if meth == "get" and kws - {"block"}:
                continue  # keyword'd non-queue .get()
            if meth in ("join", "wait", "recv", "acquire") and kws:
                continue  # acquire(blocking=False)/acquire(timeout=...) pass
            if meth == "acquire":
                recv = node.func.value
                rname = (
                    recv.attr if isinstance(recv, ast.Attribute)
                    else recv.id if isinstance(recv, ast.Name) else None
                )
                if rname not in _obs_lock_attrs():
                    continue  # not a declared obs lock: out of scope
            yield Finding(
                self.id, ctx.path, node.lineno,
                f".{meth}() with no timeout blocks forever if the peer "
                "thread (or, in fleet/, the peer PROCESS) is dead or hung "
                "— pass timeout= and surface the stall "
                "(resilience/watchdog.py), or suppress with "
                "# orion: noqa[unbounded-wait]",
            )


class SignalUnsafeHandlerRule:
    id = "signal-unsafe-handler"
    title = "I/O, lock, or checkpoint call inside a signal handler"

    # attribute calls that do buffered I/O / take locks / save state; the
    # logger-method names catch the dominant `log = logging.getLogger(...)
    # ... log.warning(...)` idiom, which locks exactly like direct
    # `logging.*` calls (in a handler, any `.info()` is a logger)
    _UNSAFE_ATTRS = frozenset({
        "write", "read", "flush", "acquire", "save", "maybe_save", "wait",
        "debug", "info", "warning", "error", "critical", "exception", "log",
    })
    # fully-dotted exemptions: the async-signal-safe raw syscalls
    _SAFE_DOTTED = frozenset({"os.write", "os.read"})
    _UNSAFE_NAMES = frozenset({"print", "open", "input"})

    def _handler_names(self, ctx: ModuleContext) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) != "signal.signal" or len(node.args) < 2:
                continue
            target = node.args[1]
            name = dotted_name(target)
            if name:
                out.add(name.rsplit(".", 1)[-1])
        return out

    def _handler_defs(self, ctx: ModuleContext) -> List[ast.AST]:
        """Registered handlers plus (fixpoint) every same-module function
        they call by name — a helper the handler calls runs in handler
        context too."""
        by_name = {}
        for fn in ctx.function_defs:
            by_name.setdefault(fn.name, []).append(fn)
        frontier = [
            fn for name in self._handler_names(ctx)
            for fn in by_name.get(name, [])
        ]
        reach: List[ast.AST] = []
        while frontier:
            fn = frontier.pop()
            if fn in reach:
                continue
            reach.append(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = dotted_name(node.func)
                    if callee:
                        frontier.extend(
                            by_name.get(callee.rsplit(".", 1)[-1], [])
                        )
        return reach

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.is_test:
            return  # tests may exercise deliberately-unsafe toy handlers
        for fn in self._handler_defs(ctx):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                unsafe = None
                if name in self._UNSAFE_NAMES:
                    unsafe = f"{name}()"
                elif name and name.split(".", 1)[0] == "logging":
                    unsafe = f"{name}() (logging locks internally)"
                elif isinstance(node.func, ast.Attribute):
                    if (
                        node.func.attr in self._UNSAFE_ATTRS
                        and name not in self._SAFE_DOTTED
                    ):
                        unsafe = f".{node.func.attr}()"
                if unsafe is None:
                    continue
                yield Finding(
                    self.id, ctx.path, node.lineno,
                    f"{unsafe} inside signal handler `{fn.name}`: handlers "
                    "run between arbitrary bytecodes and can deadlock on "
                    "io/logging locks the interrupted code holds — only "
                    "set flags (poll at step boundaries, "
                    "resilience/preempt.py) and use os.write for "
                    "last-resort messages",
                )


RULES = [UnboundedWaitRule(), SignalUnsafeHandlerRule()]
