"""Rule registry for the Tier A lint engine.

Adding a rule: write a class with ``id``, ``title``, and
``check(ctx: ModuleContext) -> Iterator[Finding]`` in one of the modules
here (or a new one), append an instance to that module's ``RULES`` list, and
import the module below. ``tests/test_analysis.py`` expects every registered
rule to have a positive and a negative fixture.

Tier D's concurrency rules (analysis/concurrency_audit.py) deliberately
do NOT register here: they run only over the four threaded packages and
carry their own fixture contract in ``tests/test_concurrency_audit.py``,
so putting them in ``ALL_RULES`` would both run them on the whole tree
and break the every-rule-has-a-fixture accounting above.
"""

from __future__ import annotations

from typing import Dict

from orion_tpu.analysis.rules import (
    concurrency,
    decode,
    hygiene,
    jit_hygiene,
    obs,
    pallas_guards,
    perf,
    persist,
)

ALL_RULES: Dict[str, object] = {}
for _mod in (jit_hygiene, perf, hygiene, pallas_guards, concurrency, decode,
             persist, obs):
    for _rule in _mod.RULES:
        assert _rule.id not in ALL_RULES, f"duplicate rule id {_rule.id}"
        ALL_RULES[_rule.id] = _rule

__all__ = ["ALL_RULES"]
