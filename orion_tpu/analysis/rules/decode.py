"""Decode-path serving rules.

``decode-host-sync`` — a host synchronization (``.block_until_ready()``,
``.item()``, ``float()``, ``np.asarray``, ``jax.device_get``) inside a
per-chunk decode loop stalls the device pipeline once per chunk: the next
chunk's dispatch waits on the readback, turning the chunked serving walk
into lockstep host-device ping-pong — the latency bug the chunked design
exists to avoid. The serving layer has exactly ONE sanctioned sync per
chunk — the all-finite probe (scalar for the solo DecodeSession, one
[slots]-bool vector for the slot-multiplexed SlotEngine) — and it lives
in a designated probe function (``DecodeSession._probe_finite``,
``SlotEngine._probe_bad``), so the rule exempts any code lexically inside
a function whose name contains ``probe``. Everything else syncs once,
after the loop.

The probe exemption is itself budgeted for the continuous-batching
scheduler loop: the per-chunk host sync must stay at ONE probe no matter
how many slots are resident. Two extra shapes are findings —

- two or more probe-function CALLS inside one decode loop body (each is
  a separate device round-trip per chunk), and
- a probe call inside a loop that is itself nested in another loop (the
  per-slot-probe shape: ``for slot in slots: self._probe(slot)`` inside
  the chunk loop syncs slot-count times per chunk).

Since ISSUE 7 the ADMISSION path is covered too: in-scan chunked prefill
makes ``admit()`` an O(1) slot insert (prompt staged into the carry, no
prefill, no readback), so any host sync inside an admission-path
function of ``serving/batching.py`` — one whose name contains ``admit``,
``insert``, or ``stage`` — is a finding even OUTSIDE a loop: admissions
sit on the scheduler's hot path and a per-admit device round-trip is the
head-of-line stall the unified path exists to kill.

ISSUE 11 adds ``prefix`` to the admission markers: the content-addressed
prefix cache's lookup/stage/publish paths in the engine
(``SlotEngine._prefix_lookup`` / ``_stage_prefix`` /
``publish_pending_prefixes``) are admission code — a hit must cost hash +
disk + ONE fused jitted row write, so any host sync in a *prefix*-named
function of ``serving/batching.py`` is the same finding. The store-side
serialization (publish's device_get) lives in
``serving/prefix_store.py`` by design, off the engine's hot path.

ISSUE 13 covers the SPECULATION path the same way: any host sync inside
a ``draft``/``verify``/``spec``-named function of ``serving/batching.py``
is a finding — the accept/reject decision must come from the existing
single per-chunk probe transfer (the accepted counts ride the same
stacked readback as the finite/done flags), never a second readback per
round; a draft pass or verify piece that syncs the host mid-boundary
re-creates exactly the lockstep ping-pong the batched round exists to
avoid. Probe-named functions remain the designated sync point.

Scope: the decode modules only (``orion_tpu/serving/`` and
``generate.py``); host loops elsewhere (eval CLIs, data prep) may sync
freely. Traced code is already covered by ``tracer-host``; this rule is
about HOST loops driving the device.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from orion_tpu.analysis.findings import Finding
from orion_tpu.analysis.lint import ModuleContext, dotted_name

_SYNC_ATTRS = frozenset({"block_until_ready", "item"})
_SYNC_NAMES = frozenset({"float"})
_SYNC_DOTTED = frozenset({
    "np.asarray", "numpy.asarray", "onp.asarray", "jax.device_get",
})


def _is_decode_module(path: str) -> bool:
    return "serving/" in path or path.endswith("generate.py")


_ADMIT_MARKERS = ("admit", "insert", "stage", "prefix")
_SPEC_MARKERS = ("draft", "verify", "spec")


def _inside_marked(node: ast.AST, markers) -> bool:
    """Lexically inside a function whose name carries one of ``markers``."""
    cur = getattr(node, "_orion_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) and any(
            m in cur.name for m in markers
        ):
            return True
        cur = getattr(cur, "_orion_parent", None)
    return False


def _inside_admission(node: ast.AST) -> bool:
    """Lexically inside an admission-path function of the engine (see
    module docstring: names containing admit/insert/stage/prefix)."""
    return _inside_marked(node, _ADMIT_MARKERS)


def _inside_spec(node: ast.AST) -> bool:
    """Lexically inside a speculation-path function of the engine (see
    module docstring: names containing draft/verify/spec)."""
    return _inside_marked(node, _SPEC_MARKERS)


def _inside_probe(node: ast.AST) -> bool:
    cur = getattr(node, "_orion_parent", None)
    while cur is not None:
        if (
            isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef))
            and "probe" in cur.name
        ):
            return True
        cur = getattr(cur, "_orion_parent", None)
    return False


def _is_probe_call(node: ast.Call) -> bool:
    """A call to a probe-named function/method (the designated sync)."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return "probe" in f.attr
    if isinstance(f, ast.Name):
        return "probe" in f.id
    return False


def _sync_label(node: ast.Call) -> Optional[str]:
    """The one place that decides 'is this call a host sync, and how do
    we print it' — shared by the loop and admission passes so the two
    budgets can never disagree on what counts as a sync."""
    name = dotted_name(node.func)
    if name in _SYNC_NAMES or name in _SYNC_DOTTED:
        return f"{name}()"
    if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_ATTRS:
        return f".{node.func.attr}()"
    return None


def _innermost_loop(node: ast.AST) -> Optional[ast.AST]:
    cur = getattr(node, "_orion_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.While)):
            return cur
        cur = getattr(cur, "_orion_parent", None)
    return None


class DecodeHostSyncRule:
    id = "decode-host-sync"
    title = "host sync inside a per-chunk decode loop"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.is_test or not _is_decode_module(ctx.path):
            return
        seen = set()
        # loop -> probe calls whose INNERMOST loop it is (a nested loop's
        # probes belong to the inner loop, so a chunk loop isn't blamed
        # for its ladder helper's probes twice)
        probes_per_loop: dict = {}
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                if _is_probe_call(node) and _innermost_loop(node) is loop:
                    if not _inside_probe(node):
                        probes_per_loop.setdefault(id(loop), (loop, []))[1].append(node)
                sync = _sync_label(node)
                if sync is None or _inside_probe(node):
                    continue
                seen.add(id(node))
                yield Finding(
                    self.id, ctx.path, node.lineno,
                    f"{sync} inside a decode loop forces a device round-"
                    "trip every chunk; sync once after the loop, or move "
                    "it into the designated probe (a function named "
                    "*probe*, e.g. DecodeSession._probe_finite)",
                )
        # the admission budget: the engine's admit/insert/stage functions
        # are sync-free — O(1) admission must not pay a device round-trip
        # per request (loop or no loop)
        if ctx.path.endswith("serving/batching.py"):
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                sync = _sync_label(node)
                if sync is None or _inside_probe(node):
                    continue
                if _inside_admission(node):
                    seen.add(id(node))
                    yield Finding(
                        self.id, ctx.path, node.lineno,
                        f"{sync} on the admission path (a function named "
                        "*admit*/*insert*/*stage*/*prefix*): admission is "
                        "an O(1) slot insert — stage the prompt (or the "
                        "cached prefix row) into the carry and let the "
                        "unified scan consume it; a per-admit host sync "
                        "re-creates the head-of-line stall (prefix-store "
                        "serialization belongs in serving/prefix_store.py)",
                    )
                elif _inside_spec(node):
                    seen.add(id(node))
                    yield Finding(
                        self.id, ctx.path, node.lineno,
                        f"{sync} on the speculation path (a function "
                        "named *draft*/*verify*/*spec*): the accept/"
                        "reject decision must ride the existing single "
                        "per-chunk probe transfer (the accepted counts "
                        "stack with the finite/done flags) — a second "
                        "readback per speculative round re-creates the "
                        "lockstep host-device ping-pong the batched "
                        "round exists to avoid",
                    )
        # the probe budget: ONE probe sync per chunk loop, slot count
        # notwithstanding (the continuous-batching scheduler contract)
        for loop, calls in probes_per_loop.values():
            if len(calls) > 1:
                yield Finding(
                    self.id, ctx.path, calls[1].lineno,
                    f"{len(calls)} probe calls in one decode loop body — "
                    "each is a separate device round-trip per chunk; fuse "
                    "them into ONE probe (stack the flags device-side, "
                    "one transfer, e.g. SlotEngine._probe_bad)",
                )
            elif _innermost_loop(loop) is not None:
                yield Finding(
                    self.id, ctx.path, calls[0].lineno,
                    "probe call in a loop nested inside a decode loop — "
                    "this syncs once PER ITERATION (per slot) per chunk; "
                    "probe the whole batch with one vectorized transfer "
                    "outside the inner loop",
                )


RULES = [DecodeHostSyncRule()]
