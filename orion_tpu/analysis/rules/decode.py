"""Decode-path serving rules.

``decode-host-sync`` — a host synchronization (``.block_until_ready()``,
``.item()``, ``float()``, ``np.asarray``, ``jax.device_get``) inside a
per-chunk decode loop stalls the device pipeline once per chunk: the next
chunk's dispatch waits on the readback, turning the chunked serving walk
into lockstep host-device ping-pong — the latency bug the chunked design
exists to avoid. The serving layer has exactly ONE sanctioned sync per
chunk — the scalar all-finite probe — and it lives in a designated probe
function (``DecodeSession._probe_finite``), so the rule exempts any code
lexically inside a function whose name contains ``probe``. Everything
else syncs once, after the loop.

Scope: the decode modules only (``orion_tpu/serving/`` and
``generate.py``); host loops elsewhere (eval CLIs, data prep) may sync
freely. Traced code is already covered by ``tracer-host``; this rule is
about HOST loops driving the device.
"""

from __future__ import annotations

import ast
from typing import Iterator

from orion_tpu.analysis.findings import Finding
from orion_tpu.analysis.lint import ModuleContext, dotted_name

_SYNC_ATTRS = frozenset({"block_until_ready", "item"})
_SYNC_NAMES = frozenset({"float"})
_SYNC_DOTTED = frozenset({
    "np.asarray", "numpy.asarray", "onp.asarray", "jax.device_get",
})


def _is_decode_module(path: str) -> bool:
    return "serving/" in path or path.endswith("generate.py")


def _inside_probe(node: ast.AST) -> bool:
    cur = getattr(node, "_orion_parent", None)
    while cur is not None:
        if (
            isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef))
            and "probe" in cur.name
        ):
            return True
        cur = getattr(cur, "_orion_parent", None)
    return False


class DecodeHostSyncRule:
    id = "decode-host-sync"
    title = "host sync inside a per-chunk decode loop"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.is_test or not _is_decode_module(ctx.path):
            return
        seen = set()
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                name = dotted_name(node.func)
                sync = None
                if name in _SYNC_NAMES:
                    sync = f"{name}()"
                elif name in _SYNC_DOTTED:
                    sync = f"{name}()"
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_ATTRS
                ):
                    sync = f".{node.func.attr}()"
                if sync is None or _inside_probe(node):
                    continue
                seen.add(id(node))
                yield Finding(
                    self.id, ctx.path, node.lineno,
                    f"{sync} inside a decode loop forces a device round-"
                    "trip every chunk; sync once after the loop, or move "
                    "it into the designated probe (a function named "
                    "*probe*, e.g. DecodeSession._probe_finite)",
                )


RULES = [DecodeHostSyncRule()]
