"""Durability-hygiene rules for state that must survive a kill.

``non-atomic-persist`` — a state file written in place (``open(path, "w")``
                     + dump) is torn by any kill mid-write: the next
                     process reads half a JSON and dies — or worse,
                     silently mis-parses. Every state publish under the
                     serving//resilience//training subtrees must use the
                     write-tmp-then-``os.replace`` idiom (one helper:
                     ``training/checkpoint.py::atomic_write_json``), which
                     makes the rename the commit point: readers see the
                     previous complete file or the new complete file,
                     never a prefix. This is the invariant the whole
                     durable-session / checkpoint-manifest fault model
                     leans on — the chaos tests kill writers mid-save and
                     expect the previous generation intact.

Heuristics (AST-only): a ``open(..., "w"/"wb"/"w+")`` call (positional or
``mode=`` keyword, string literal) inside one of the persistence subtrees
is a finding unless the enclosing function also calls ``os.replace`` /
``os.rename`` (the tmp-write of the idiom lives in the same function as
its publishing rename). Append mode is exempt — an append-only log
(metrics jsonl) is prefix-valid by construction, no rename can help it.
Reads are exempt. Test code is exempt. Real exceptions use the standard
``# orion: noqa[non-atomic-persist]`` / baseline escape hatch.

``raw-store-io`` — the shared-storage clients (``session_store.py``,
                     ``prefix_store.py``) route every syscall through
                     breaker-gated ``_io_*`` helpers: each helper checks
                     ``CircuitBreaker.blocked()`` before touching the
                     filesystem, so an open breaker means zero disk probes
                     on the hot path (the whole point of the failure-domain
                     design — a dead NFS mount must not stall chunk_ms).
                     A direct ``open()`` / ``os.replace`` / ``os.listdir``
                     call anywhere else in those modules bypasses the gate:
                     it reintroduces a blocking syscall the outage regime
                     can hang for seconds, invisible to the breaker's
                     failure accounting. Heuristic (AST-only): flag those
                     three calls in the two store modules unless the
                     enclosing function is itself an ``_io_`` helper.
                     Test code is exempt; real exceptions use
                     ``# orion: noqa[raw-store-io]`` / the baseline.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from orion_tpu.analysis.findings import Finding
from orion_tpu.analysis.lint import ModuleContext, dotted_name

_PERSIST_SUBTREES = ("serving/", "resilience/", "training/")


def _write_mode(call: ast.Call) -> Optional[str]:
    """The literal mode string of an ``open`` call iff it truncate-writes
    ('w' anywhere in the mode); None for reads, appends, r+ updates, or
    non-literal modes (no type info — don't guess)."""
    mode_node = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if mode_node is None:
        return None
    if not (isinstance(mode_node, ast.Constant)
            and isinstance(mode_node.value, str)):
        return None
    return mode_node.value if "w" in mode_node.value else None


class NonAtomicPersistRule:
    id = "non-atomic-persist"
    title = "state file written without write-tmp-then-os.replace"

    def _enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = getattr(node, "_orion_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = getattr(cur, "_orion_parent", None)
        return None

    @staticmethod
    def _has_publish_rename(scope: ast.AST) -> bool:
        for node in ast.walk(scope):
            if isinstance(node, ast.Call) and dotted_name(node.func) in (
                "os.replace", "os.rename",
            ):
                return True
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.is_test:
            return
        if not any(s in ctx.path for s in _PERSIST_SUBTREES):
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and dotted_name(node.func) == "open"):
                continue
            mode = _write_mode(node)
            if mode is None:
                continue
            scope = self._enclosing_function(node) or ctx.tree
            if self._has_publish_rename(scope):
                continue  # the write-tmp-then-replace idiom
            yield Finding(
                self.id, ctx.path, node.lineno,
                f"open(..., {mode!r}) publishes a state file in place: a "
                "kill mid-write leaves a torn file the next process "
                "chokes on — write a sibling .tmp and os.replace it into "
                "place (training/checkpoint.py::atomic_write_json), or "
                "suppress with # orion: noqa[non-atomic-persist]",
            )


_STORE_MODULES = ("session_store.py", "prefix_store.py", "exec_store.py")

# The syscalls the stores actually issue on their hot paths. os.makedirs at
# construction time is deliberately not listed: it runs once, before the
# breaker exists, and failing there is a config error, not an outage.
_RAW_STORE_CALLS = ("open", "os.replace", "os.listdir")


class RawStoreIORule:
    id = "raw-store-io"
    title = "store syscall outside a breaker-gated _io_* helper"

    _enclosing_function = NonAtomicPersistRule._enclosing_function

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.is_test:
            return
        if not ctx.path.endswith(_STORE_MODULES):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name not in _RAW_STORE_CALLS:
                continue
            scope = self._enclosing_function(node)
            if (scope is not None
                    and scope.name.startswith("_io_")):
                continue  # the sanctioned breaker-gated helper itself
            yield Finding(
                self.id, ctx.path, node.lineno,
                f"{name}(...) hits the store filesystem without the "
                "breaker gate: route it through an _io_* helper (which "
                "checks CircuitBreaker.blocked() first) so an open "
                "breaker means zero syscalls on the request path, or "
                "suppress with # orion: noqa[raw-store-io]",
            )


RULES = [NonAtomicPersistRule(), RawStoreIORule()]
