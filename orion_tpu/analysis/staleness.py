"""Stale-suppression audit (ISSUE 18 satellite): suppressions must decay.

A ``# orion: noqa[rule-id]`` that no longer suppresses anything, or a
baseline.json entry whose (rule, path) no longer matches any finding, is a
muted alarm wired to nothing — it hides the NEXT genuine finding at that
site. After the tiers run, this module re-examines every suppression
against the findings that were actually produced (``keep_suppressed``
mode, so live noqas show up as ``status="suppressed"``) and reports the
dead ones:

- **stale-noqa** — a noqa comment whose rule ids produced no finding on
  its logical line. Only ids belonging to rules that actually RAN this
  invocation are judged (a ``--tier lint`` run must not call a Tier D
  noqa stale); bare ``# orion: noqa`` and unknown rule ids are judged
  only on a full run (``--tier all`` over the whole package).
- **dead-baseline-entry** — a baseline entry whose rule ran over its
  file and produced nothing. ``--prune-baseline`` rewrites the baseline
  minus the dead entries, preserving the rationales of the live ones.
- **dead-exec-entry** (ISSUE 20) — a serialized executable in an AOT
  exec store whose ProgramDecl no longer exists (or drifted): content
  addressing means the live universe hashes to different keys, so the
  entry is unreachable forever. ``python -m orion_tpu.serving.exec_store
  gc`` prunes them.

Suppression comments are found by TOKENIZING, not by regexing raw lines:
the noqa pattern appears inside docstrings and string literals all over
the analysis package itself, and only a real COMMENT token is a
suppression."""

from __future__ import annotations

import io
import json
import os
import tokenize
from typing import Dict, FrozenSet, Iterable, List, Sequence, Set, Tuple

from orion_tpu.analysis.findings import (
    BaselineEntry,
    Finding,
    normalize_path,
)
from orion_tpu.analysis.lint import (
    NOQA_ALL,
    NOQA_RE,
    ModuleContext,
    iter_py_files,
)

RULE_STALE_NOQA = "stale-noqa"
RULE_DEAD_BASELINE = "dead-baseline-entry"
RULE_DEAD_EXEC = "dead-exec-entry"

ALL_STALENESS_CHECKS = (
    RULE_STALE_NOQA, RULE_DEAD_BASELINE, RULE_DEAD_EXEC,
)


def _noqa_comments(source: str) -> List[Tuple[int, FrozenSet[str]]]:
    """(line, rule ids) for each REAL ``# orion: noqa`` comment token."""
    out: List[Tuple[int, FrozenSet[str]]] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = NOQA_RE.search(tok.string)
            if not m:
                continue
            ids = m.group(1)
            out.append((
                tok.start[0],
                frozenset(
                    s.strip() for s in ids.split(",") if s.strip()
                ) if ids else NOQA_ALL,
            ))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []  # unparseable file: the parse-error finding owns it
    return out


def stale_noqa_findings(
    findings: Sequence[Finding],
    paths: Sequence[str],
    ran_rule_ids: Iterable[str],
    root: str = "",
    full: bool = False,
) -> List[Finding]:
    """Judge every noqa comment under ``paths`` against ``findings``
    (which must include suppressed ones — a suppressed finding is the
    proof its noqa is alive)."""
    ran = frozenset(ran_rule_ids)
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            continue
        comments = _noqa_comments(source)
        if not comments:
            continue
        try:
            ctx = ModuleContext(source, path, root)
        except SyntaxError:
            continue
        if ctx.is_test:
            continue  # fixture noqas in tests are data, not suppressions
        file_findings = by_path.get(ctx.path, [])
        for line, ids in comments:
            span = ctx.logical_lines.get(line, range(line, line + 1))
            hit_rules: Set[str] = {
                f.rule for f in file_findings if f.line in span
            }
            if ids is NOQA_ALL:
                if full and not hit_rules:
                    out.append(Finding(
                        RULE_STALE_NOQA, ctx.path, line,
                        "bare `# orion: noqa` suppresses nothing on this "
                        "line — remove it (and prefer targeted "
                        "`noqa[rule-id]` if it ever comes back)",
                    ))
                continue
            for rid in sorted(ids):
                if rid in ran:
                    if rid not in hit_rules:
                        out.append(Finding(
                            RULE_STALE_NOQA, ctx.path, line,
                            f"`# orion: noqa[{rid}]` no longer "
                            "suppresses anything — the finding it muted "
                            "is gone; remove the comment so the next "
                            f"real `{rid}` here is not silently eaten",
                        ))
                elif full:
                    out.append(Finding(
                        RULE_STALE_NOQA, ctx.path, line,
                        f"`# orion: noqa[{rid}]` names a rule id no "
                        "tier defines — a typo here mutes nothing and "
                        "hides intent; fix or remove it",
                    ))
    return out


def dead_baseline_entries(
    findings: Sequence[Finding],
    baseline: Sequence[BaselineEntry],
    ran_rule_ids: Iterable[str],
    audited_paths: Sequence[str] = (),
) -> List[BaselineEntry]:
    """Entries whose rule ran over their file yet matched nothing.
    ``findings`` must be the keep-suppressed/annotated set (baselined
    findings prove their entry is alive). ``audited_paths`` are
    repo-relative prefixes this run actually covered; entries outside
    them are never judged."""
    ran = frozenset(ran_rule_ids)
    live = {(f.rule, f.path) for f in findings}
    prefixes = tuple(p.rstrip("/") for p in audited_paths)

    def audited(path: str) -> bool:
        if not prefixes:
            return True
        return any(
            path == p or path.startswith(p + "/") for p in prefixes
        )

    return [
        b for b in baseline
        if b.rule in ran and audited(b.path)
        and (b.rule, b.path) not in live
    ]


def dead_baseline_findings(
    dead: Sequence[BaselineEntry], baseline_path: str, root: str = ""
) -> List[Finding]:
    rel = normalize_path(baseline_path, root)
    return [
        Finding(
            RULE_DEAD_BASELINE, rel, 0,
            f"baseline entry (rule `{b.rule}`, path `{b.path}`) matches "
            "no finding — the grandfathered problem is fixed; remove "
            "the entry (or rerun with --prune-baseline) so the next "
            f"`{b.rule}` in that file gates again. Rationale was: "
            f"{b.reason}",
        )
        for b in dead
    ]


def dead_exec_entries(entries: Sequence[dict]) -> List[dict]:
    """Manifests from an exec store (``ExecStore.entries()``) that
    nothing in the DECLARED compile universe can ever address again
    (ISSUE 20 satellite): the kind is no longer a decode-section
    ProgramDecl, or the kind's declaration drifted since publication
    (``decl_fingerprint`` is part of the content address, so the live
    universe now hashes to a different key and this entry is
    unreachable disk forever). Same decay principle as a dead baseline
    entry — an address nothing resolves to is storage wired to
    nothing. Prunable via ``python -m orion_tpu.serving.exec_store
    gc``."""
    from orion_tpu.serving.exec_store import decl_fingerprint

    out = []
    for doc in entries:
        kind = str((doc.get("ident") or {}).get("kind", ""))
        current = decl_fingerprint(kind)
        if current.startswith("undeclared:") or doc.get("decl") != current:
            out.append(doc)
    return out


def dead_exec_findings(
    dead: Sequence[dict], store_dir: str, root: str = ""
) -> List[Finding]:
    rel = normalize_path(store_dir, root)
    out = []
    for doc in dead:
        kind = str((doc.get("ident") or {}).get("kind", ""))
        current_gone = decl_exists = False
        try:
            from orion_tpu.analysis.programs import PROGRAMS

            decl_exists = any(
                d.name == kind and d.section == "decode" for d in PROGRAMS
            )
        except Exception:
            pass
        current_gone = not decl_exists
        out.append(Finding(
            RULE_DEAD_EXEC, rel, 0,
            f"exec store entry `{doc.get('key')}` (kind `{kind}`) is "
            + ("for a kind no decode ProgramDecl declares"
               if current_gone else
               "addressed under a SUPERSEDED declaration of its kind — "
               "the live universe hashes to a different key")
            + "; nothing can ever hit it again. Prune with `python -m "
            "orion_tpu.serving.exec_store gc`",
        ))
    return out


def prune_baseline(
    baseline_path: str, dead: Sequence[BaselineEntry]
) -> int:
    """Rewrite the baseline minus ``dead``, preserving the reasons (and
    any unknown keys) of surviving entries verbatim. Returns the number
    of entries removed."""
    if not dead or not os.path.exists(baseline_path):
        return 0
    with open(baseline_path, encoding="utf-8") as f:
        data = json.load(f)
    drop = {(b.rule, b.path) for b in dead}
    kept = [
        e for e in data.get("entries", [])
        if (e.get("rule"), e.get("path")) not in drop
    ]
    removed = len(data.get("entries", [])) - len(kept)
    if removed:
        data["entries"] = kept
        with open(baseline_path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2)
            f.write("\n")
    return removed


__all__ = [
    "ALL_STALENESS_CHECKS", "RULE_DEAD_BASELINE", "RULE_DEAD_EXEC",
    "RULE_STALE_NOQA", "dead_baseline_entries", "dead_baseline_findings",
    "dead_exec_entries", "dead_exec_findings", "prune_baseline",
    "stale_noqa_findings",
]
