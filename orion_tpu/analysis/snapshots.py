"""Tier C (part 2): golden compile-artifact snapshots.

Budget checks (spmd_audit.py) see the jaxpr; this module looks one layer
down, at what XLA actually compiled. Each target in
:data:`SNAPSHOT_TARGETS` is lowered and compiled on the deterministic
8-virtual-CPU-device mesh and summarized into a small JSON artifact:

- ``op_histogram``     — optimized-HLO opcode counts (fusions included):
  the compiled program's shape, insensitive to register names.
- ``hlo_collectives``  — all-reduce / all-gather / reduce-scatter /
  all-to-all / collective-permute counts in the optimized HLO — the
  communication GSPMD actually inserted from the shardings (the jaxpr of
  the auto-sharded train step shows none of these).
- ``scan_carry_bytes`` — byte size of the largest scan's carry (the
  decode target's O(1)-state budget in bytes).
- ``dtype_counts``     — occurrences of each element-type token in the
  optimized HLO (``s8[...]``, ``f32[...]``, ...): the artifact that pins
  a quantized program's storage story — the int8/int4 decode targets
  must show ``s8`` weight traffic while their scan carry stays the fp32
  target's EXACT byte size (weights quantize, state never does).
- ``flops`` / ``bytes_accessed`` — the compiler's own cost model.
- ``donation``         — declared donated input buffers vs the aliases
  XLA accepted. A donated arg XLA refuses to alias silently doubles that
  buffer's HBM footprint: surfaced as ``donated-arg-unaliased``.

Snapshots are stored under ``orion_tpu/analysis/golden/`` and regenerated
with ``python -m orion_tpu.analysis --update-golden``. The audit recompiles
each target and diffs against the stored file with a human-readable delta,
so any PR that changes the compiled program must either update the golden
file (making the change reviewable) or fail tier-1:

- ``golden-snapshot-missing`` — no stored artifact for a target.
- ``golden-snapshot-drift``   — stored vs fresh mismatch (delta in the
  finding message).

Generation is deterministic on CPU: same jax/jaxlib + same config =>
byte-identical JSON (asserted by tests regenerating in-process).
"""

from __future__ import annotations

import collections
import json
import os
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from orion_tpu.analysis.findings import Finding
from orion_tpu.analysis.jaxpr_audit import AUDIT_ERROR, scan_carry_avals
from orion_tpu.analysis.spmd_audit import ensure_cpu_devices

RULE_DRIFT = "golden-snapshot-drift"
RULE_MISSING = "golden-snapshot-missing"
RULE_DONATION = "donated-arg-unaliased"

ALL_GOLDEN_CHECKS = (RULE_DRIFT, RULE_MISSING, RULE_DONATION)

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")

_MAX_DELTA_LINES = 20


# -- HLO text extraction ------------------------------------------------------

# "%name = shape opcode(...)" — shape is either a bare token or a tuple
_OP_RE = re.compile(
    r"(?m)^\s*(?:ROOT\s+)?%?[\w.\-]+ = (?:\([^)]*\)|\S+) ([a-z][a-z0-9\-]*)\("
)

_HLO_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)


def op_histogram(hlo_text: str) -> Dict[str, int]:
    return dict(sorted(collections.Counter(_OP_RE.findall(hlo_text)).items()))


# element-type tokens as they appear in HLO shapes ("s8[128,64]{...}")
_DTYPE_RE = re.compile(r"\b(pred|s4|u4|s8|u8|s16|u16|s32|u32|s64|u64|"
                       r"bf16|f16|f32|f64)\[")


def dtype_counts(hlo_text: str) -> Dict[str, int]:
    """Shape-dtype token histogram of the optimized HLO — how often each
    element type appears in an instruction shape. Coarse by design: it
    pins that a quantized program actually streams int8 buffers (s8 > 0)
    and that the fp32 program has none, without depending on how XLA
    fuses the dequant convert into the dot."""
    return dict(sorted(
        collections.Counter(_DTYPE_RE.findall(hlo_text)).items()
    ))


def hlo_collective_counts(hlo_text: str) -> Dict[str, int]:
    return {
        op: len(re.findall(rf"\b{op}(?:-start)?\(", hlo_text))
        for op in _HLO_COLLECTIVES
    }


def alias_count(hlo_text: str) -> int:
    """Input/output aliases XLA ACCEPTED (entry-computation
    ``input_output_alias`` entries)."""
    return hlo_text.count("may-alias") + hlo_text.count("must-alias")


def _carry_bytes(closed_jaxpr) -> Optional[int]:
    import numpy as np

    carries = scan_carry_avals(closed_jaxpr.jaxpr)
    if carries is None:
        return None
    total = 0
    for shape, dtype in carries:
        n = int(np.prod(shape)) if shape else 1
        total += n * np.dtype(dtype).itemsize
    return total


def _cost_ints(compiled) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    try:
        ca = compiled.cost_analysis()
        ca0 = ca[0] if isinstance(ca, (list, tuple)) else ca
        for key, name in (("flops", "flops"), ("bytes accessed", "bytes_accessed")):
            v = ca0.get(key)
            if v is not None:
                out[name] = int(v)
    except Exception as e:  # backend-dependent introspection
        out["cost_analysis_error"] = f"{type(e).__name__}: {e}"[:120]
    return out


# -- targets ------------------------------------------------------------------


def _snap_train_tiny_dp8() -> Tuple[Any, Any, Dict[str, Any]]:
    """The donated, GSPMD-sharded tiny train step on the dp=8 mesh — the
    artifact that proves the sharding rules engage (all-reduces present)
    and donation aliases (state updated in place). Built from the SAME
    trainer the Tier C budget audit traces (spmd_audit.tiny_dp8_trainer)
    so budget and snapshot can never drift onto different programs."""
    import jax

    from orion_tpu.analysis.spmd_audit import tiny_dp8_trainer

    tr, batch = tiny_dp8_trainer()
    jaxpr = jax.make_jaxpr(tr._train_step)(tr._abstract, batch)
    lowered = tr._step_fn.lower(tr.abstract_state(), batch)
    meta = {
        "mesh": {k: int(v) for k, v in tr.mesh.shape.items()},
        "batch_size": tr.cfg.batch_size,
        "seq_len": tr.cfg.seq_len,
        # _step_fn donates the whole TrainState (donate_argnums=(0,))
        "donated_args": len(jax.tree.leaves(tr.abstract_state())),
    }
    return jaxpr, lowered, meta


def _snap_decode_tiny() -> Tuple[Any, Any, Dict[str, Any]]:
    """The jitted recurrent decode step — the O(1)-state artifact (its
    scan carry bytes ARE the per-token state budget)."""
    import jax
    import jax.numpy as jnp

    from orion_tpu.generate import SampleConfig, _generate_jit
    from orion_tpu.models.configs import get_config
    from orion_tpu.models.transformer import TransformerLM

    model = TransformerLM(get_config("tiny"))
    key = jax.random.PRNGKey(0)
    prompt = jax.ShapeDtypeStruct((1, 8), jnp.int32)
    params = jax.eval_shape(model.init, key, prompt)
    fn = jax.jit(_generate_jit, static_argnums=(0, 3, 4))
    args = (model, params, prompt, 8, SampleConfig(), key)
    jaxpr = jax.make_jaxpr(_generate_jit, static_argnums=(0, 3, 4))(*args)
    lowered = fn.lower(*args)
    meta = {"prompt_len": 8, "max_new_tokens": 8, "donated_args": 0}
    return jaxpr, lowered, meta


def _snap_decode_batched_tiny() -> Tuple[Any, Any, Dict[str, Any]]:
    """The slot-multiplexed batched decode chunk (continuous batching,
    serving/batching.py SlotEngine) at slots=8, chunk=8 — the artifact
    that pins the engine's compiled shape: scan-carry bytes must scale
    LINEARLY in the slot count (each slot is one row of the O(1) state —
    no paged-KV overhead) and the collective count stays zero (decode
    never communicates). tests/test_batching.py asserts the linearity
    against a slots=1 jaxpr rebuild."""
    from functools import partial

    import jax
    import jax.numpy as jnp

    from orion_tpu.generate import SampleConfig, _decode_batched_chunk_jit
    from orion_tpu.models.configs import get_config
    from orion_tpu.models.transformer import TransformerLM, init_decode_state

    cfg = get_config("tiny")
    model = TransformerLM(cfg)
    slots, chunk = 8, 8
    key = jax.random.PRNGKey(0)
    prompt = jax.ShapeDtypeStruct((1, 8), jnp.int32)
    params = jax.eval_shape(model.init, key, prompt)
    states = jax.eval_shape(partial(init_decode_state, cfg, slots))
    vec = lambda dt: jax.ShapeDtypeStruct((slots,), dt)  # noqa: E731
    carry = (
        vec(jnp.int32), states, vec(jnp.int32), vec(jnp.int32),
        vec(jnp.bool_),
    )
    rngs = jax.ShapeDtypeStruct((slots, 2), jnp.uint32)
    active = vec(jnp.bool_)
    args = (model, params, carry, rngs, active, chunk, SampleConfig())
    jaxpr = jax.make_jaxpr(
        _decode_batched_chunk_jit, static_argnums=(0, 5, 6)
    )(*args)
    lowered = _decode_batched_chunk_jit.lower(*args)
    meta = {"slots": slots, "chunk": chunk, "donated_args": 0}
    return jaxpr, lowered, meta


def _snap_decode_batched_prefill_tiny() -> Tuple[Any, Any, Dict[str, Any]]:
    """The UNIFIED in-scan prefill + decode chunk (ISSUE 7,
    generate.decode_batched_prefill_chunk) at slots=8, chunk=8,
    prompt_bucket=16 — the program the engine runs while any slot is
    mid-prefill. Pins three things: the scan-carry bytes stay LINEAR in
    the slot count (the staged prompt buffer rides OUTSIDE the scan
    carry — prefill must not fatten the O(1) decode state), collectives
    stay zero, and — because the staging path is a separate jit — the
    pure-decode program (``decode_batched_tiny``) keeps compiling
    byte-identically when no slot is prefilling."""
    from functools import partial

    import jax
    import jax.numpy as jnp

    from orion_tpu.generate import (
        SampleConfig,
        _decode_batched_prefill_chunk_jit,
    )
    from orion_tpu.models.configs import get_config
    from orion_tpu.models.transformer import TransformerLM, init_decode_state

    cfg = get_config("tiny")
    model = TransformerLM(cfg)
    slots, chunk, bucket, pchunk = 8, 8, 16, 128
    key = jax.random.PRNGKey(0)
    prompt = jax.ShapeDtypeStruct((1, 8), jnp.int32)
    params = jax.eval_shape(model.init, key, prompt)
    states = jax.eval_shape(partial(init_decode_state, cfg, slots))
    vec = lambda dt: jax.ShapeDtypeStruct((slots,), dt)  # noqa: E731
    carry = (
        vec(jnp.int32), states, vec(jnp.int32), vec(jnp.int32),
        vec(jnp.bool_),
    )
    rngs = jax.ShapeDtypeStruct((slots, 2), jnp.uint32)
    active = vec(jnp.bool_)
    pbuf = jax.ShapeDtypeStruct((slots, bucket), jnp.int32)
    args = (
        model, params, carry, rngs, active, pbuf, vec(jnp.int32),
        vec(jnp.int32), chunk, pchunk, SampleConfig(),
    )
    jaxpr = jax.make_jaxpr(
        _decode_batched_prefill_chunk_jit, static_argnums=(0, 8, 9, 10)
    )(*args)
    lowered = _decode_batched_prefill_chunk_jit.lower(*args)
    meta = {
        "slots": slots, "chunk": chunk, "prompt_bucket": bucket,
        "prefill_chunk": pchunk, "donated_args": 0,
    }
    return jaxpr, lowered, meta


def _snap_decode_batched_quant(mode: str) -> Tuple[Any, Any, Dict[str, Any]]:
    """The slot-multiplexed batched decode chunk compiled over the QUANT
    model (``TransformerLM(cfg, quant=mode)``) at the same slots=8,
    chunk=8 shape as ``decode_batched_tiny`` — the quantized-serving
    artifact (ISSUE 11). Three pins: collectives stay zero, the scan
    carry bytes are EXACTLY the fp32 target's (the carry is tokens +
    decode state + bookkeeping; weights quantize, the carry must not
    grow or shrink with qmode), and ``dtype_counts`` shows the s8 weight
    traffic (int4 packs nibbles into s8 bytes too — halving shows up in
    buffer SIZES, which the op/dtype mix reflects via the unpack ops)."""
    from functools import partial

    import jax
    import jax.numpy as jnp

    from orion_tpu.generate import SampleConfig, _decode_batched_chunk_jit
    from orion_tpu.models.configs import get_config
    from orion_tpu.models.transformer import TransformerLM, init_decode_state

    cfg = get_config("tiny")
    model = TransformerLM(cfg, quant=mode)
    slots, chunk = 8, 8
    key = jax.random.PRNGKey(0)
    prompt = jax.ShapeDtypeStruct((1, 8), jnp.int32)
    params = jax.eval_shape(model.init, key, prompt)
    states = jax.eval_shape(partial(init_decode_state, cfg, slots))
    vec = lambda dt: jax.ShapeDtypeStruct((slots,), dt)  # noqa: E731
    carry = (
        vec(jnp.int32), states, vec(jnp.int32), vec(jnp.int32),
        vec(jnp.bool_),
    )
    rngs = jax.ShapeDtypeStruct((slots, 2), jnp.uint32)
    active = vec(jnp.bool_)
    args = (model, params, carry, rngs, active, chunk, SampleConfig())
    jaxpr = jax.make_jaxpr(
        _decode_batched_chunk_jit, static_argnums=(0, 5, 6)
    )(*args)
    lowered = _decode_batched_chunk_jit.lower(*args)
    meta = {"slots": slots, "chunk": chunk, "qmode": mode,
            "donated_args": 0}
    return jaxpr, lowered, meta


def _snap_decode_batched_spec_tiny() -> Tuple[Any, Any, Dict[str, Any]]:
    """The self-speculative round (ISSUE 13,
    generate.decode_batched_spec_round) at slots=8, spec depth=4 on the
    tiny config — the artifact that pins the draft-verify program's
    shape: collectives stay ZERO (speculation never communicates), and
    the largest scan carry must NOT exceed the plain batched decode's —
    the draft scan threads the SAME (S, z) rows (shadow copies of the
    carry's own leaves, no growth) and the verify's inner scans carry
    one layer's state at a time. tests/test_analysis.py asserts the
    no-growth bound against ``decode_batched_tiny`` and
    tests/test_spec_decode.py the slot-linearity of the carry."""
    from functools import partial

    import jax
    import jax.numpy as jnp

    from orion_tpu.generate import SampleConfig, _decode_batched_spec_round_jit
    from orion_tpu.models.configs import get_config
    from orion_tpu.models.transformer import TransformerLM, init_decode_state

    cfg = get_config("tiny")
    model = TransformerLM(cfg)
    slots, depth = 8, 4
    key = jax.random.PRNGKey(0)
    prompt = jax.ShapeDtypeStruct((1, 8), jnp.int32)
    params = jax.eval_shape(model.init, key, prompt)
    states = jax.eval_shape(partial(init_decode_state, cfg, slots))
    vec = lambda dt: jax.ShapeDtypeStruct((slots,), dt)  # noqa: E731
    carry = (
        vec(jnp.int32), states, vec(jnp.int32), vec(jnp.int32),
        vec(jnp.bool_),
    )
    rngs = jax.ShapeDtypeStruct((slots, 2), jnp.uint32)
    active = vec(jnp.bool_)
    spec_on = vec(jnp.bool_)
    args = (
        model, params, carry, rngs, active, spec_on, depth, SampleConfig(),
    )
    jaxpr = jax.make_jaxpr(
        _decode_batched_spec_round_jit, static_argnums=(0, 6, 7)
    )(*args)
    lowered = _decode_batched_spec_round_jit.lower(*args)
    meta = {"slots": slots, "spec_depth": depth, "donated_args": 0}
    return jaxpr, lowered, meta


def _snap_decode_batched_tp(tp: int) -> Tuple[Any, Any, Dict[str, Any]]:
    """The slot-multiplexed batched decode chunk compiled under a tp=N
    mesh (ISSUE 14, SlotEngine(mesh=...)): params sharded by the training
    rules, state head-sharded, per-slot vectors replicated. Four pins:

    - ``hlo_collectives``: exactly the Megatron contract — TWO
      all-reduces per block per decode step (wo + down), nothing else
      (the head-sharded state and the qkv/gate/up output shards
      communicate nothing). A third collective appearing here is a
      leaked per-token cost no CPU parity test would catch.
    - ``scan_carry_bytes_per_device``: the head-sharded state divides by
      tp while only the few per-slot bookkeeping vectors replicate —
      tests/test_analysis.py asserts it against the unsharded
      ``decode_batched_tiny`` carry.
    - the collectives live INSIDE the decode scan's while-loop body
      (they depend on each step's activations — there is nothing to
      hoist), so program-level counts ARE per-step counts.
    - dtype_counts/op_histogram: the partitioned program's shape.

    The trace fixtures are shared with the Tier C budget audit
    (spmd_audit.tp_decode_pieces) so budget and snapshot can never drift
    onto different programs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from orion_tpu.analysis.spmd_audit import tp_decode_pieces
    from orion_tpu.generate import SampleConfig, _decode_batched_chunk_jit
    from orion_tpu.parallel.decode import bytes_per_device

    slots, chunk = 8, 8
    model, params, carry, rngs, vec, shardings = tp_decode_pieces(
        tp=tp, slots=slots
    )
    (p_abs, p_shd), (st_abs, st_shd), _mesh = shardings
    args = (model, params, carry, rngs, vec(jnp.bool_), chunk, SampleConfig())
    jaxpr = jax.make_jaxpr(
        _decode_batched_chunk_jit, static_argnums=(0, 5, 6)
    )(*args)
    lowered = _decode_batched_chunk_jit.lower(*args)
    # per-device carry bytes from the PLACEMENT (shape arithmetic, no
    # compile): sharded state / tp + the replicated per-slot vectors
    state_dev = bytes_per_device(st_abs, st_shd)
    vec_bytes = slots * (3 * np.dtype(np.int32).itemsize + 1)
    meta = {
        "slots": slots, "chunk": chunk, "mesh": {"tp": tp},
        "param_bytes_per_device": bytes_per_device(p_abs, p_shd),
        "scan_carry_bytes_per_device": state_dev + vec_bytes,
        "donated_args": 0,
    }
    return jaxpr, lowered, meta


def _snap_decode_batched_tp2():
    return _snap_decode_batched_tp(2)


def _snap_decode_batched_tp4():
    return _snap_decode_batched_tp(4)


def _snap_decode_batched_int8():
    return _snap_decode_batched_quant("int8")


def _snap_decode_batched_int4():
    return _snap_decode_batched_quant("int4")


# name -> () -> (closed_jaxpr, lowered, meta). Golden files live at
# golden/<name>.json; adding a target here + --update-golden creates one.
SNAPSHOT_TARGETS: Dict[str, Callable[[], Tuple[Any, Any, Dict[str, Any]]]] = {
    "train_tiny_dp8": _snap_train_tiny_dp8,
    "decode_tiny": _snap_decode_tiny,
    "decode_batched_tiny": _snap_decode_batched_tiny,
    "decode_batched_prefill_tiny": _snap_decode_batched_prefill_tiny,
    "decode_batched_spec_tiny": _snap_decode_batched_spec_tiny,
    "decode_batched_int8": _snap_decode_batched_int8,
    "decode_batched_int4": _snap_decode_batched_int4,
    "decode_batched_tp2": _snap_decode_batched_tp2,
    "decode_batched_tp4": _snap_decode_batched_tp4,
}


def build_snapshot(name: str) -> Dict[str, Any]:
    jaxpr, lowered, meta = SNAPSHOT_TARGETS[name]()
    compiled = lowered.compile()
    hlo = compiled.as_text()
    snap: Dict[str, Any] = {
        "target": name,
        **meta,
        "op_histogram": op_histogram(hlo),
        "dtype_counts": dtype_counts(hlo),
        "hlo_collectives": hlo_collective_counts(hlo),
        "scan_carry_bytes": _carry_bytes(jaxpr),
        "donation": {
            "donated_args": meta.get("donated_args", 0),
            "aliased": alias_count(hlo),
        },
    }
    snap.pop("donated_args", None)
    snap.update(_cost_ints(compiled))
    return snap


# -- diff + audit -------------------------------------------------------------


def _flatten(d: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k in sorted(d):
        key = f"{prefix}{k}"
        if isinstance(d[k], dict):
            out.update(_flatten(d[k], key + "."))
        else:
            out[key] = d[k]
    return out


def diff_report(golden: Dict[str, Any], fresh: Dict[str, Any]) -> List[str]:
    """Human-readable delta lines, golden -> fresh; empty == identical."""
    g, f = _flatten(golden), _flatten(fresh)
    lines = []
    for k in sorted(set(g) | set(f)):
        if k not in g:
            lines.append(f"+ {k} = {f[k]!r} (not in golden)")
        elif k not in f:
            lines.append(f"- {k} = {g[k]!r} (gone from fresh build)")
        elif g[k] != f[k]:
            lines.append(f"~ {k}: {g[k]!r} -> {f[k]!r}")
    return lines


def donation_findings(snap: Dict[str, Any], path: str) -> List[Finding]:
    """A donated buffer XLA refused to alias is a live memory regression
    regardless of what the golden file says — checked at build time."""
    d = snap.get("donation") or {}
    donated, aliased = d.get("donated_args", 0), d.get("aliased", 0)
    if donated and aliased < donated:
        return [Finding(
            RULE_DONATION, path, 0,
            f"{snap.get('target', path)}: {donated} donated input "
            f"buffer(s) but XLA aliased only {aliased} — each refused "
            "alias keeps both the argument and the output live "
            "(double HBM for that buffer); check dtype/sharding changes "
            "to the donated state",
        )]
    return []


def golden_path(name: str, golden_dir: str = GOLDEN_DIR) -> str:
    return os.path.join(golden_dir, f"{name}.json")


def write_golden(name: str, snap: Dict[str, Any], golden_dir: str = GOLDEN_DIR) -> str:
    os.makedirs(golden_dir, exist_ok=True)
    p = golden_path(name, golden_dir)
    with open(p, "w", encoding="utf-8") as f:
        json.dump(snap, f, indent=2, sort_keys=True)
        f.write("\n")
    return p


def audit_golden(
    update: bool = False,
    golden_dir: str = GOLDEN_DIR,
    fresh: Optional[Dict[str, Dict[str, Any]]] = None,
) -> List[Finding]:
    """Rebuild every snapshot target and diff against the checked-in golden
    files (``update=True`` rewrites them instead). ``fresh`` supplies
    prebuilt snapshots (tests share one expensive build across cases)."""
    err = ensure_cpu_devices()
    if err is not None:
        return [Finding(AUDIT_ERROR, "<golden>", 0, err)]

    findings: List[Finding] = []
    for name in SNAPSHOT_TARGETS:
        rel = f"orion_tpu/analysis/golden/{name}.json"
        try:
            snap = fresh[name] if fresh and name in fresh else build_snapshot(name)
        except Exception as e:  # noqa: BLE001 - surfaced as finding, not crash
            findings.append(Finding(
                AUDIT_ERROR, f"<golden:{name}>", 0,
                f"building snapshot {name} failed: {type(e).__name__}: {e}",
            ))
            continue
        findings.extend(donation_findings(snap, rel))
        if update:
            write_golden(name, snap, golden_dir)
            continue
        gp = golden_path(name, golden_dir)
        if not os.path.exists(gp):
            findings.append(Finding(
                RULE_MISSING, rel, 0,
                f"no golden snapshot for {name}; run "
                "`python -m orion_tpu.analysis --update-golden` and commit "
                "the result",
            ))
            continue
        with open(gp, encoding="utf-8") as f:
            golden = json.load(f)
        delta = diff_report(golden, snap)
        if delta:
            shown = delta[:_MAX_DELTA_LINES]
            if len(delta) > len(shown):
                shown.append(f"... {len(delta) - len(shown)} more line(s)")
            findings.append(Finding(
                RULE_DRIFT, rel, 0,
                f"compiled artifact for {name} drifted from its golden "
                f"snapshot ({len(delta)} delta line(s)):\n    "
                + "\n    ".join(shown)
                + "\n    intentional? rerun with --update-golden and commit "
                "the new snapshot so the change is reviewed",
            ))
    return findings


__all__ = [
    "audit_golden", "build_snapshot", "diff_report", "donation_findings",
    "op_histogram", "dtype_counts", "hlo_collective_counts",
    "alias_count", "write_golden",
    "golden_path", "SNAPSHOT_TARGETS", "GOLDEN_DIR", "ALL_GOLDEN_CHECKS",
    "RULE_DRIFT", "RULE_MISSING", "RULE_DONATION",
]
