"""``python -m orion_tpu.analysis`` — run the analysis tiers; exit non-zero
on any finding that is neither ``# orion: noqa[rule-id]``-suppressed nor
baselined (analysis/baseline.json) with a rationale.

Tiers: A = AST lint, B = jaxpr contracts, C = SPMD collective budgets
(``--tier spmd``) + golden compile-artifact snapshots (``--tier golden``),
D = concurrency audit over the threaded serving stack
(``--tier concurrency``: declared lock hierarchy, held-lock discipline,
guarded-state — serving/locks.py is the declaration).
``--update-golden`` regenerates the snapshots under analysis/golden/ for
PRs that intentionally change the compiled program. ``--format json``
emits machine-readable findings (suppressed/baselined included, with
status) for CI and bots. ``--self-time`` prints per-tier wall time to
stderr — the suite lives inside the 870s tier-1 gate and must be kept
honest about where the seconds go."""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "orion_tpu.analysis",
        description="orion-tpu static analysis: AST lint + jaxpr contracts "
        "+ SPMD collective budgets + golden compile snapshots",
    )
    p.add_argument(
        "paths", nargs="*",
        help="files/dirs to lint (default: the orion_tpu package)",
    )
    p.add_argument(
        "--tier",
        choices=["lint", "jaxpr", "spmd", "golden", "concurrency", "all"],
        default="all",
        help="lint = Tier A AST rules; jaxpr = Tier B contract audit "
        "(traces the train/LRA/decode steps on abstract shapes); spmd = "
        "Tier C collective-budget audit (traces the sharded paths under "
        "an abstract 8-device mesh); golden = Tier C compile-artifact "
        "snapshot diff; concurrency = Tier D lock-discipline audit of "
        "the threaded serving stack (pure AST — never imports or "
        "executes the audited code, zero traces/compiles/device work)",
    )
    p.add_argument(
        "--baseline", default=None,
        help="baseline JSON (default: orion_tpu/analysis/baseline.json); "
        "'none' disables baselining",
    )
    p.add_argument(
        "--format", choices=["text", "json"], default="text",
        help="json: one object per finding (rule, path, line, message, "
        "status incl. suppressed/baselined) — for CI consumption",
    )
    p.add_argument(
        "--update-golden", action="store_true",
        help="regenerate the golden compile-artifact snapshots "
        "(orion_tpu/analysis/golden/) and exit — for PRs that "
        "intentionally change the compiled program",
    )
    p.add_argument(
        "--golden-dir", default=None,
        help="override the golden snapshot directory (tests)",
    )
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule/contract catalog and exit")
    p.add_argument(
        "--self-time", action="store_true",
        help="print per-tier wall time to stderr (runtime-budget "
        "accounting for the tier-1 gate)",
    )
    args = p.parse_args(argv)

    # Tier C traces/compiles against the abstract 8-virtual-CPU-device
    # mesh; configure jax BEFORE anything initializes a backend (tier B
    # would otherwise bring up a 1-device world first)
    if args.update_golden or args.tier in ("spmd", "golden", "all"):
        from orion_tpu.analysis.spmd_audit import ensure_cpu_devices

        ensure_cpu_devices()

    from orion_tpu.analysis import concurrency_audit
    from orion_tpu.analysis.findings import (
        DEFAULT_BASELINE,
        Finding,
        annotate_baseline,
        apply_baseline,
        load_baseline,
    )
    from orion_tpu.analysis.lint import lint_paths
    from orion_tpu.analysis.rules import ALL_RULES

    # B/C modules trace and compile at audit time; a pure Tier D (or A)
    # run must stay AST-only — zero traces, zero compiles, zero syncs
    need_jax_tiers = (
        args.update_golden or args.list_rules
        or args.tier in ("jaxpr", "spmd", "golden", "all")
    )
    if need_jax_tiers:
        from orion_tpu.analysis import jaxpr_audit, snapshots, spmd_audit

    if args.list_rules:
        print("Tier A (AST lint):")
        for rule in ALL_RULES.values():
            print(f"  {rule.id:<20} {rule.title}")
        print("Tier B (jaxpr contracts):")
        for cid in jaxpr_audit.ALL_CONTRACTS:
            print(f"  {cid}")
        print("Tier C (SPMD budgets + golden snapshots):")
        for cid in spmd_audit.ALL_SPMD_CHECKS + snapshots.ALL_GOLDEN_CHECKS:
            print(f"  {cid}")
        print("Tier D (concurrency audit, serving/locks.py declaration):")
        for rule in concurrency_audit.concurrency_rules():
            print(f"  {rule.id:<20} {rule.title}")
        return 0

    golden_dir = args.golden_dir or (
        snapshots.GOLDEN_DIR if need_jax_tiers else None
    )
    if args.update_golden:
        findings = snapshots.audit_golden(update=True, golden_dir=golden_dir)
        if args.format == "json":
            print(json.dumps({
                "updated": sorted(snapshots.SNAPSHOT_TARGETS),
                "golden_dir": golden_dir,
                "findings": [f.to_json() for f in findings],
            }, indent=2))
        else:
            for f in findings:
                print(f.format())
            print(
                f"golden snapshots regenerated under {golden_dir} — commit "
                "them with the PR that changes the compiled program"
            )
        return 1 if findings else 0

    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    paths = args.paths or [os.path.join(repo_root, "orion_tpu")]

    if args.baseline == "none":
        baseline = []
    else:
        baseline = load_baseline(args.baseline or DEFAULT_BASELINE)

    keep = args.format == "json"

    def finish(fs: List[Finding]) -> List[Finding]:
        """Baseline the non-lint tiers (lint_paths baselines internally)."""
        return (
            annotate_baseline(fs, baseline)
            if keep
            else apply_baseline(fs, baseline)
        )

    self_times: List = []

    def timed(label: str, fn):
        t0 = time.perf_counter()
        out = fn()
        self_times.append((label, time.perf_counter() - t0))
        return out

    findings: List[Finding] = []
    if args.tier in ("lint", "all"):
        findings += timed("tier A (lint)", lambda: lint_paths(
            paths, baseline=baseline, root=repo_root, keep_suppressed=keep
        ))
    if args.tier in ("jaxpr", "all"):
        findings += timed(
            "tier B (jaxpr)", lambda: finish(jaxpr_audit.audit_repo())
        )
    if args.tier in ("spmd", "all"):
        findings += timed(
            "tier C (spmd)", lambda: finish(spmd_audit.audit_spmd())
        )
    if args.tier in ("golden", "all"):
        findings += timed("tier C (golden)", lambda: finish(
            snapshots.audit_golden(golden_dir=golden_dir)
        ))
    if args.tier in ("concurrency", "all"):
        findings += timed(
            "tier D (concurrency)",
            lambda: concurrency_audit.audit_concurrency(
                root=repo_root, baseline=baseline, keep_suppressed=keep
            ),
        )

    if args.self_time:
        for label, dt in self_times:
            print(f"self-time: {label:<22} {dt:8.2f}s", file=sys.stderr)
        print(
            f"self-time: {'total':<22} "
            f"{sum(dt for _, dt in self_times):8.2f}s",
            file=sys.stderr,
        )

    active = [f for f in findings if f.status == "active"]
    tiers = {
        "lint": "tier A", "jaxpr": "tier B", "spmd": "tier C/spmd",
        "golden": "tier C/golden", "concurrency": "tier D",
        "all": "tiers A+B+C+D",
    }
    if args.format == "json":
        doc = {
            "tier": args.tier,
            "findings": [f.to_json() for f in findings],
            "counts": {
                "active": len(active),
                "suppressed": sum(
                    1 for f in findings if f.status == "suppressed"
                ),
                "baselined": sum(
                    1 for f in findings if f.status == "baselined"
                ),
            },
        }
        print(json.dumps(doc, indent=2))
        return 1 if active else 0

    for f in active:
        print(f.format())
    n = len(active)
    if n:
        print(
            f"\n{n} finding(s) ({tiers[args.tier]}). Fix them, suppress a "
            "false positive in-line with `# orion: noqa[rule-id]`, baseline "
            "it with a reason in orion_tpu/analysis/baseline.json, or — for "
            "an intentional compiled-program change — rerun with "
            "--update-golden and commit the new snapshot.",
            file=sys.stderr,
        )
        return 1
    print(f"analysis clean ({tiers[args.tier]})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
