"""``python -m orion_tpu.analysis`` — run both analysis tiers; exit non-zero
on any finding that is neither ``# orion: noqa[rule-id]``-suppressed nor
baselined (analysis/baseline.json) with a rationale."""

from __future__ import annotations

import argparse
import os
import sys
from typing import List


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "orion_tpu.analysis",
        description="orion-tpu static analysis: AST lint + jaxpr contracts",
    )
    p.add_argument(
        "paths", nargs="*",
        help="files/dirs to lint (default: the orion_tpu package)",
    )
    p.add_argument(
        "--tier", choices=["lint", "jaxpr", "all"], default="all",
        help="lint = Tier A AST rules only; jaxpr = Tier B contract audit "
        "only (traces the train/LRA/decode steps on abstract shapes)",
    )
    p.add_argument(
        "--baseline", default=None,
        help="baseline JSON (default: orion_tpu/analysis/baseline.json); "
        "'none' disables baselining",
    )
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule/contract catalog and exit")
    args = p.parse_args(argv)

    from orion_tpu.analysis import jaxpr_audit
    from orion_tpu.analysis.findings import (
        DEFAULT_BASELINE,
        Finding,
        apply_baseline,
        load_baseline,
    )
    from orion_tpu.analysis.lint import lint_paths
    from orion_tpu.analysis.rules import ALL_RULES

    if args.list_rules:
        print("Tier A (AST lint):")
        for rule in ALL_RULES.values():
            print(f"  {rule.id:<20} {rule.title}")
        print("Tier B (jaxpr contracts):")
        for cid in jaxpr_audit.ALL_CONTRACTS:
            print(f"  {cid}")
        return 0

    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    paths = args.paths or [os.path.join(repo_root, "orion_tpu")]

    if args.baseline == "none":
        baseline = []
    else:
        baseline = load_baseline(args.baseline or DEFAULT_BASELINE)

    findings: List[Finding] = []
    if args.tier in ("lint", "all"):
        findings += lint_paths(paths, baseline=baseline, root=repo_root)
    if args.tier in ("jaxpr", "all"):
        findings += apply_baseline(jaxpr_audit.audit_repo(), baseline)

    for f in findings:
        print(f.format())
    n = len(findings)
    tiers = {"lint": "tier A", "jaxpr": "tier B", "all": "tiers A+B"}
    if n:
        print(
            f"\n{n} finding(s) ({tiers[args.tier]}). Fix them, suppress a "
            "false positive in-line with `# orion: noqa[rule-id]`, or "
            "baseline it with a reason in orion_tpu/analysis/baseline.json.",
            file=sys.stderr,
        )
        return 1
    print(f"analysis clean ({tiers[args.tier]})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
