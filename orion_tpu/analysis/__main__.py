"""``python -m orion_tpu.analysis`` — run the analysis tiers; exit non-zero
on any finding that is neither ``# orion: noqa[rule-id]``-suppressed nor
baselined (analysis/baseline.json) with a rationale.

Tiers: A = AST lint, B = jaxpr contracts, C = SPMD collective budgets
(``--tier spmd``) + golden compile-artifact snapshots (``--tier golden``),
D = concurrency audit over the threaded serving stack
(``--tier concurrency``: declared lock hierarchy, held-lock discipline,
guarded-state — serving/locks.py is the declaration), E = closed
compile-universe audit (``--tier programs``: every jit registered in
analysis/programs.py, static key spaces finite, aot.decode_plan in sync,
donation pinned — pure AST plus memoized lowering, never executes).
After the tiers, a staleness pass judges the suppressions themselves:
a noqa that mutes nothing or a baseline entry matching no finding is a
finding (``--prune-baseline`` rewrites the baseline minus dead entries).
``--update-golden`` regenerates the snapshots under analysis/golden/ for
PRs that intentionally change the compiled program. ``--format json``
emits machine-readable findings (suppressed/baselined included, with
status) plus a per-tier summary trailer (``"tiers"``) so CI logs show
which tier gated. ``--self-time`` prints per-tier wall time to stderr —
the suite lives inside the 870s tier-1 gate and must be kept honest
about where the seconds go."""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List

TIER_LABELS = {
    "lint": "tier A", "jaxpr": "tier B", "spmd": "tier C/spmd",
    "golden": "tier C/golden", "concurrency": "tier D",
    "programs": "tier E", "suppressions": "staleness",
    "all": "tiers A+B+C+D+E",
}


def tier_summary_lines(rows: List[Dict]) -> List[str]:
    """The ``--tier all`` per-tier summary table (text mode). ``rows``
    are the same dicts the json ``"tiers"`` trailer carries."""
    header = (
        f"{'tier':<22} {'active':>6} {'suppr':>6} {'basel':>6} "
        f"{'seconds':>8}"
    )
    out = [header, "-" * len(header)]
    for r in rows:
        out.append(
            f"{r['label']:<22} {r['active']:>6} {r['suppressed']:>6} "
            f"{r['baselined']:>6} {r['seconds']:>8.2f}"
        )
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "orion_tpu.analysis",
        description="orion-tpu static analysis: AST lint + jaxpr contracts "
        "+ SPMD collective budgets + golden compile snapshots + "
        "concurrency audit + compile-universe audit",
    )
    p.add_argument(
        "paths", nargs="*",
        help="files/dirs to lint (default: the orion_tpu package)",
    )
    p.add_argument(
        "--tier",
        choices=[
            "lint", "jaxpr", "spmd", "golden", "concurrency", "programs",
            "all",
        ],
        default="all",
        help="lint = Tier A AST rules; jaxpr = Tier B contract audit "
        "(traces the train/LRA/decode steps on abstract shapes); spmd = "
        "Tier C collective-budget audit (traces the sharded paths under "
        "an abstract 8-device mesh); golden = Tier C compile-artifact "
        "snapshot diff; concurrency = Tier D lock-discipline audit of "
        "the threaded serving stack (pure AST — never imports or "
        "executes the audited code); programs = Tier E compile-universe "
        "audit (every jit declared in analysis/programs.py, static key "
        "spaces finite, decode_plan/donation in sync — AST plus "
        "memoized lowering, never executes)",
    )
    p.add_argument(
        "--baseline", default=None,
        help="baseline JSON (default: orion_tpu/analysis/baseline.json); "
        "'none' disables baselining",
    )
    p.add_argument(
        "--format", choices=["text", "json"], default="text",
        help="json: one object per finding (rule, path, line, message, "
        "status incl. suppressed/baselined) plus a per-tier 'tiers' "
        "summary trailer — for CI consumption",
    )
    p.add_argument(
        "--update-golden", action="store_true",
        help="regenerate the golden compile-artifact snapshots "
        "(orion_tpu/analysis/golden/) and exit — for PRs that "
        "intentionally change the compiled program",
    )
    p.add_argument(
        "--golden-dir", default=None,
        help="override the golden snapshot directory (tests)",
    )
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule/contract catalog and exit")
    p.add_argument(
        "--self-time", action="store_true",
        help="print per-tier wall time to stderr (runtime-budget "
        "accounting for the tier-1 gate)",
    )
    p.add_argument(
        "--prune-baseline", action="store_true",
        help="rewrite the baseline file minus entries that no longer "
        "match any finding (rationales of live entries are preserved), "
        "then continue the run without the pruned dead-entry findings",
    )
    args = p.parse_args(argv)

    # Tier C traces/compiles against the abstract 8-virtual-CPU-device
    # mesh; configure jax BEFORE anything initializes a backend (tier B
    # would otherwise bring up a 1-device world first)
    if args.update_golden or args.tier in ("spmd", "golden", "all"):
        from orion_tpu.analysis.spmd_audit import ensure_cpu_devices

        ensure_cpu_devices()

    from orion_tpu.analysis import concurrency_audit, program_audit
    from orion_tpu.analysis import staleness as stale
    from orion_tpu.analysis.findings import (
        DEFAULT_BASELINE,
        Finding,
        load_baseline,
    )
    from orion_tpu.analysis.lint import lint_paths
    from orion_tpu.analysis.rules import ALL_RULES

    # B/C modules trace and compile at audit time; a pure Tier A/D/E run
    # must stay import-light — Tier E itself only touches jax inside the
    # memoized lowering pass
    need_jax_tiers = (
        args.update_golden or args.list_rules
        or args.tier in ("jaxpr", "spmd", "golden", "all")
    )
    if need_jax_tiers:
        from orion_tpu.analysis import jaxpr_audit, snapshots, spmd_audit

    if args.list_rules:
        print("Tier A (AST lint):")
        for rule in ALL_RULES.values():
            print(f"  {rule.id:<20} {rule.title}")
        print("Tier B (jaxpr contracts):")
        for cid in jaxpr_audit.ALL_CONTRACTS:
            print(f"  {cid}")
        print("Tier C (SPMD budgets + golden snapshots):")
        for cid in spmd_audit.ALL_SPMD_CHECKS + snapshots.ALL_GOLDEN_CHECKS:
            print(f"  {cid}")
        print("Tier D (concurrency audit, serving/locks.py declaration):")
        for rule in concurrency_audit.concurrency_rules():
            print(f"  {rule.id:<20} {rule.title}")
        print("Tier E (compile universe, analysis/programs.py "
              "declaration):")
        for rule in program_audit.program_rules():
            print(f"  {rule.id:<20} {rule.title}")
        print(f"  {program_audit.RULE_PLAN:<20} "
              "decode_plan inventory vs declared universe")
        print(f"  {program_audit.RULE_DONATION:<20} "
              "donate_argnums vs declaration vs golden snapshots")
        print("Staleness (suppressions must decay):")
        for cid in stale.ALL_STALENESS_CHECKS:
            print(f"  {cid}")
        return 0

    golden_dir = args.golden_dir or (
        snapshots.GOLDEN_DIR if need_jax_tiers else None
    )
    if args.update_golden:
        findings = snapshots.audit_golden(update=True, golden_dir=golden_dir)
        if args.format == "json":
            print(json.dumps({
                "updated": sorted(snapshots.SNAPSHOT_TARGETS),
                "golden_dir": golden_dir,
                "findings": [f.to_json() for f in findings],
            }, indent=2))
        else:
            for f in findings:
                print(f.format())
            print(
                f"golden snapshots regenerated under {golden_dir} — commit "
                "them with the PR that changes the compiled program"
            )
        return 1 if findings else 0

    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    paths = args.paths or [os.path.join(repo_root, "orion_tpu")]

    baseline_path = None
    if args.baseline == "none":
        baseline = []
    else:
        baseline_path = args.baseline or DEFAULT_BASELINE
        baseline = load_baseline(baseline_path)

    # every tier runs keep-suppressed internally so the staleness pass
    # can see which suppressions are alive; text mode filters at the end
    tier_rows: List[Dict] = []
    findings: List[Finding] = []

    def run_tier(tier: str, fn) -> None:
        t0 = time.perf_counter()
        fs = fn()
        tier_rows.append({
            "tier": tier,
            "label": TIER_LABELS[tier],
            "active": sum(1 for f in fs if f.status == "active"),
            "suppressed": sum(1 for f in fs if f.status == "suppressed"),
            "baselined": sum(1 for f in fs if f.status == "baselined"),
            "seconds": time.perf_counter() - t0,
        })
        findings.extend(fs)

    def finish(fs: List[Finding]) -> List[Finding]:
        """Baseline the tiers that don't do it internally (B/C)."""
        from orion_tpu.analysis.findings import annotate_baseline

        return annotate_baseline(fs, baseline)

    if args.tier in ("lint", "all"):
        run_tier("lint", lambda: lint_paths(
            paths, baseline=baseline, root=repo_root, keep_suppressed=True
        ))
    if args.tier in ("jaxpr", "all"):
        run_tier("jaxpr", lambda: finish(jaxpr_audit.audit_repo()))
    if args.tier in ("spmd", "all"):
        run_tier("spmd", lambda: finish(spmd_audit.audit_spmd()))
    if args.tier in ("golden", "all"):
        run_tier("golden", lambda: finish(
            snapshots.audit_golden(golden_dir=golden_dir)
        ))
    if args.tier in ("concurrency", "all"):
        run_tier(
            "concurrency",
            lambda: concurrency_audit.audit_concurrency(
                root=repo_root, baseline=baseline, keep_suppressed=True
            ),
        )
    if args.tier in ("programs", "all"):
        run_tier(
            "programs",
            lambda: program_audit.audit_programs(
                root=repo_root, baseline=baseline, keep_suppressed=True
            ),
        )

    # -- staleness pass: judge the suppressions against what just ran ----
    ran_ids: List[str] = []
    stale_paths: List[str] = []
    audited_rel: List[str] = []
    ran_tiers = {r["tier"] for r in tier_rows}
    if "lint" in ran_tiers:
        ran_ids += list(ALL_RULES.keys())
        stale_paths += list(paths)
        from orion_tpu.analysis.findings import normalize_path

        audited_rel += [normalize_path(p, repo_root) for p in paths]
    if "concurrency" in ran_tiers:
        ran_ids += [r.id for r in concurrency_audit.concurrency_rules()]
        stale_paths += [
            os.path.join(repo_root, p)
            for p in concurrency_audit.TIER_D_PACKAGES
        ]
        audited_rel += list(concurrency_audit.TIER_D_PACKAGES)
    if "programs" in ran_tiers:
        ran_ids += list(program_audit.ALL_PROGRAM_CHECKS)
        stale_paths += [
            os.path.join(repo_root, p) for p in program_audit.TIER_E_PATHS
        ]
        audited_rel += list(program_audit.TIER_E_PATHS)
    # B/C contract findings live on synthetic "<target>" paths, not
    # noqa-suppressable source lines — their ids stay out of the judging
    # set so a partial run never calls their baselines dead
    if ran_ids:
        full = args.tier == "all" and not args.paths
        t0 = time.perf_counter()
        seen = set()
        uniq = [
            q for q in stale_paths
            if not (q in seen or seen.add(q))
        ]
        stale_fs = stale.stale_noqa_findings(
            findings, uniq, ran_ids, root=repo_root, full=full
        )
        dead = stale.dead_baseline_entries(
            findings, baseline, ran_ids, audited_rel
        )
        if dead and args.prune_baseline and baseline_path:
            removed = stale.prune_baseline(baseline_path, dead)
            print(
                f"pruned {removed} dead baseline entr"
                f"{'y' if removed == 1 else 'ies'} from {baseline_path}",
                file=sys.stderr,
            )
            dead = []
        stale_fs += stale.dead_baseline_findings(
            dead, baseline_path or DEFAULT_BASELINE, repo_root
        )
        if stale_fs:
            tier_rows.append({
                "tier": "suppressions",
                "label": TIER_LABELS["suppressions"],
                "active": len(stale_fs),
                "suppressed": 0, "baselined": 0,
                "seconds": time.perf_counter() - t0,
            })
            findings.extend(stale_fs)

    if args.self_time:
        for r in tier_rows:
            print(
                f"self-time: {r['label']:<22} {r['seconds']:8.2f}s",
                file=sys.stderr,
            )
        print(
            f"self-time: {'total':<22} "
            f"{sum(r['seconds'] for r in tier_rows):8.2f}s",
            file=sys.stderr,
        )

    active = [f for f in findings if f.status == "active"]
    if args.format == "json":
        doc = {
            "tier": args.tier,
            "findings": [f.to_json() for f in findings],
            "counts": {
                "active": len(active),
                "suppressed": sum(
                    1 for f in findings if f.status == "suppressed"
                ),
                "baselined": sum(
                    1 for f in findings if f.status == "baselined"
                ),
            },
            "tiers": tier_rows,
        }
        print(json.dumps(doc, indent=2))
        return 1 if active else 0

    for f in active:
        print(f.format())
    if args.tier == "all":
        for line in tier_summary_lines(tier_rows):
            print(line, file=sys.stderr)
    n = len(active)
    if n:
        print(
            f"\n{n} finding(s) ({TIER_LABELS[args.tier]}). Fix them, "
            "suppress a false positive in-line with a targeted noqa "
            "comment, baseline it with a reason in "
            "orion_tpu/analysis/baseline.json, or — for an intentional "
            "compiled-program change — rerun with --update-golden and "
            "commit the new snapshot.",
            file=sys.stderr,
        )
        return 1
    print(f"analysis clean ({TIER_LABELS[args.tier]})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
