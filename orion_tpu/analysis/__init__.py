"""Static analysis for orion-tpu: AST lint + jaxpr contracts + SPMD audits.

Five tiers, one CLI (``python -m orion_tpu.analysis``), all part of tier-1
via tests/test_analysis.py:

- **Tier A** (analysis/lint.py, analysis/rules/): AST lint over the package —
  JAX hazards (debug calls and tracer materialization under jit, unhashable
  static args, Python-loop jnp accumulation in hot paths, float64 leaks) and
  repo contracts (pallas chunk guards, mutable defaults, bare excepts,
  unbounded waits, signal-unsafe handlers).
- **Tier B** (analysis/jaxpr_audit.py): traces — never executes — the jitted
  train step, the LRA step, and the recurrent decode step on abstract shapes
  and asserts the declared contracts (collective-free O(1)-state decode,
  bf16 matmul policy, no host callbacks).
- **Tier C** (analysis/spmd_audit.py, analysis/snapshots.py): traces the
  sharded programs (dp train step, sp/ring attention paths, pipeline step)
  under an abstract 8-device mesh and checks every collective against the
  per-step budgets declared in parallel/budgets.py; lowers audited configs
  to HLO and diffs op histogram / collectives / scan-carry bytes / cost
  model / donation aliasing against golden snapshots (analysis/golden/,
  regenerated via ``--update-golden``).
- **Tier D** (analysis/concurrency_audit.py): pure-AST lock-discipline audit
  of the threaded serving stack against the declared hierarchy in
  serving/locks.py — acquisition order, held-lock bans, guarded-state
  writes, undeclared locks, scope creep.
- **Tier E** (analysis/program_audit.py): the compile universe is closed —
  every jit/shard_map in generate.py, serving/, parallel/ is declared in
  analysis/programs.py with a finite static key space; aot.decode_plan's
  inventory, the DECODE_PROGRAMS registry, and the declared donation all
  stay in sync (pure AST plus one memoized lowering, never executes).

Suppression: ``# orion: noqa[rule-id]`` on (any physical line of) the
finding's logical line; grandfathered findings live in analysis/baseline.json
with a mandatory rationale. ``--format json`` emits machine-readable
findings with suppressed/baselined status plus a per-tier ``"tiers"``
summary for CI. A post-run staleness pass (analysis/staleness.py) flags
suppressions that no longer suppress anything (stale-noqa,
dead-baseline-entry; ``--prune-baseline`` rewrites the baseline minus the
dead entries).
"""

from orion_tpu.analysis.findings import (  # noqa: F401
    BaselineEntry,
    Finding,
    apply_baseline,
    load_baseline,
)
from orion_tpu.analysis.lint import lint_paths, lint_source  # noqa: F401

__all__ = [
    "Finding", "BaselineEntry", "load_baseline", "apply_baseline",
    "lint_source", "lint_paths",
]
