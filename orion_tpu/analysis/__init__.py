"""Static analysis for orion-tpu: AST lint rules + jaxpr contract audits.

Two tiers, one CLI (``python -m orion_tpu.analysis``), both part of tier-1
via tests/test_analysis.py:

- **Tier A** (analysis/lint.py, analysis/rules/): AST lint over the package —
  JAX hazards (debug calls and tracer materialization under jit, unhashable
  static args, Python-loop jnp accumulation in hot paths, float64 leaks) and
  repo contracts (pallas chunk guards, mutable defaults, bare excepts).
- **Tier B** (analysis/jaxpr_audit.py): traces — never executes — the jitted
  train step, the LRA step, and the recurrent decode step on abstract shapes
  and asserts the declared contracts (collective-free O(1)-state decode,
  bf16 matmul policy, no host callbacks).

Suppression: ``# orion: noqa[rule-id]`` on the finding's line; grandfathered
findings live in analysis/baseline.json with a mandatory rationale.
"""

from orion_tpu.analysis.findings import (  # noqa: F401
    BaselineEntry,
    Finding,
    apply_baseline,
    load_baseline,
)
from orion_tpu.analysis.lint import lint_paths, lint_source  # noqa: F401

__all__ = [
    "Finding", "BaselineEntry", "load_baseline", "apply_baseline",
    "lint_source", "lint_paths",
]
