"""Shared finding/baseline plumbing for both analysis tiers.

A ``Finding`` is one violation: a rule id, a repo-relative location, and a
message. Tier A (analysis/lint.py) produces them from AST checks; tier B
(analysis/jaxpr_audit.py) from traced-jaxpr contracts. The CLI
(``python -m orion_tpu.analysis``) exits non-zero on any finding that is
neither suppressed in-line (``# orion: noqa[rule-id]``) nor grandfathered in
the baseline file.

Baseline format (analysis/baseline.json)::

    {"entries": [{"rule": "<rule-id>", "path": "<repo-relative>",
                  "reason": "<why this is a false positive>"}]}

Entries match every finding of ``rule`` in ``path`` — file granularity, so
baselines survive unrelated line churn. ``reason`` is mandatory: a baseline
without a rationale is just a muted alarm.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Iterable, List, Sequence

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative posix path (or "<target>" for jaxpr audits)
    line: int
    message: str
    # "active" findings gate the CLI; "suppressed" (in-line noqa) and
    # "baselined" ones are carried only by the machine-readable output
    # (--format json) so CI/bots see the full picture, and default-compare
    # equal to pre-status findings everywhere else
    status: str = "active"

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "message": self.message, "status": self.status,
        }


def normalize_path(path: str, root: str = "") -> str:
    """Repo-relative posix form so baselines/noqa match on any machine."""
    p = os.path.abspath(path)
    root = os.path.abspath(root or os.getcwd())
    if p.startswith(root + os.sep):
        p = p[len(root) + 1:]
    return p.replace(os.sep, "/")


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    rule: str
    path: str
    reason: str


def load_baseline(path: str = DEFAULT_BASELINE) -> List[BaselineEntry]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    entries = []
    for e in data.get("entries", []):
        if not e.get("reason", "").strip():
            raise ValueError(
                f"baseline entry {e!r} has no reason; every grandfathered "
                "finding must say why it is a false positive"
            )
        entries.append(
            BaselineEntry(rule=e["rule"], path=e["path"], reason=e["reason"])
        )
    return entries


def annotate_baseline(
    findings: Iterable[Finding], baseline: Sequence[BaselineEntry]
) -> List[Finding]:
    """Mark grandfathered findings ``status="baselined"`` instead of
    dropping them — the --format json path, where CI wants to see muted
    alarms too. Already-suppressed findings keep their status."""
    keys = {(b.rule, b.path) for b in baseline}
    return [
        dataclasses.replace(f, status="baselined")
        if f.status == "active" and (f.rule, f.path) in keys
        else f
        for f in findings
    ]


def apply_baseline(
    findings: Iterable[Finding], baseline: Sequence[BaselineEntry]
) -> List[Finding]:
    return [
        f for f in annotate_baseline(findings, baseline)
        if f.status != "baselined"
    ]


__all__ = [
    "Finding", "BaselineEntry", "load_baseline", "apply_baseline",
    "annotate_baseline", "normalize_path", "DEFAULT_BASELINE",
]
