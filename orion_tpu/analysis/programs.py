"""Tier E declaration: the compile universe is CLOSED (ISSUE 18).

ROADMAP item 1's executable store only works if a scaled-up replica can
download its programs instead of compiling them — which requires that the
set of jit entrypoints is statically known and every static-argument key
space is finite and enumerable. This module is where that claim is made
*as data*, the way ``serving/locks.py`` declares the lock hierarchy for
Tier D: every ``jax.jit`` / ``shard_map`` site in ``generate.py`` /
``serving/`` / ``parallel/`` has a :class:`ProgramDecl` row, every static
parameter draws from a domain named in :data:`FINITE_DOMAINS`, and
``analysis/program_audit.py`` (Tier E, ``--tier programs``) checks the
code against the table — an undeclared jit, an unbounded static key, or
a drifted ``aot.decode_plan`` inventory is a CI finding.

Sections:

- ``decode`` — the serving universe proper: exactly the programs
  ``generate.DECODE_PROGRAMS`` registers and ``aot.decode_plan``
  inventories. Their per-footprint applicability is declared on the row
  (``plan=``) so :func:`expected_decode_universe` can reproduce the plan
  from declarations alone and the plan-drift rule has an independent
  side to diff against.
- ``solo`` — the batch/CLI decode path (``generate()``); not part of a
  serving replica's universe but still registered so a new jit there is
  a conscious act.
- ``setup`` — one-shot construction-time programs (engine row ops,
  quantization): compiled once per process, no per-request key growth.
- ``training`` — the train-side ``shard_map`` launchers; their key
  spaces follow the training config, not serving traffic
  (``keyspace="open"`` with the rationale on the row).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Tuple

GENERATE = "orion_tpu/generate.py"
BATCHING = "orion_tpu/serving/batching.py"


@dataclasses.dataclass(frozen=True)
class ProgramDecl:
    """One declared jit/shard_map program.

    ``qualname`` is the jit-wrapper def name for decorated functions, or
    the ENCLOSING def name for bare ``jax.jit(...)`` / ``shard_map(...)``
    call sites (module-level sites use the assignment target name).
    ``static_args`` are the wrapper's static parameter NAMES in
    static_argnums order — the audit cross-checks them against the AST so
    the declaration cannot silently drift. ``plan`` declares the
    program's per-footprint applicability in ``aot.decode_plan``:
    ``always`` / ``per_bucket`` / ``per_bucket_unified`` (one per bucket,
    only when the in-scan prefill budget is on) / ``spec`` (only with
    spec_depth > 0) / ``never`` (reachable but deliberately unplanned —
    say why in ``note``) / ``unplanned`` (not a decode-section program).
    ``keyspace="open"`` exempts the row from the unbounded-static-key
    rule; the note must say why an unbounded key space is acceptable.
    ``goldens`` are the compile-artifact snapshots whose donation counts
    pin this program's ``donate_argnums``.
    """

    name: str
    module: str
    qualname: str
    section: str  # "decode" | "solo" | "setup" | "training"
    static_args: Tuple[str, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    keyspace: str = "closed"  # "closed" | "open"
    plan: str = "unplanned"
    goldens: Tuple[str, ...] = ()
    note: str = ""


# Every static-parameter name that is allowed to be a jit key, mapped to
# the finite domain it draws from. A static parameter whose name is NOT
# here must be proven finite by the interprocedural call-site trace
# (config-attribute reads, literals, declared module constants) or it is
# an unbounded-static-key finding.
FINITE_DOMAINS: Dict[str, str] = {
    "model": "the served TransformerLM — one per deployed ModelConfig",
    "sample_cfg": "SampleConfig — a deployment's sampling presets; the "
                  "batched programs take ONE config for all slots, so the "
                  "key space is the preset count, not the request count",
    "sample": "alias of sample_cfg at the public wrappers",
    "n_steps": "the serve chunk knob (ServeConfig.chunk / --chunk); one "
               "value per engine lifetime",
    "chunk": "the serve chunk knob",
    "slots": "the engine slot count — fixed at construction",
    "pchunk": "the aligned in-scan prefill budget (SlotEngine rounds "
              "prefill_chunk up to chunk_align; one value per engine)",
    "prefill_chunk": "the in-scan prefill budget knob",
    "bucket": "a declared prefill bucket width (parse_buckets)",
    "depth": "the speculative-decode depth knob (--spec-depth)",
    "spec_depth": "the speculative-decode depth knob",
}

# Attribute reads rooted at these names classify as finite in the
# call-site trace: they are config/deployment state, not request state.
FINITE_ATTR_BASES = frozenset({
    "self", "cfg", "config", "args", "model_cfg", "serve_cfg", "CFG",
})


_DECODE_STATICS = ("model", "n_steps", "sample_cfg")

PROGRAMS: Tuple[ProgramDecl, ...] = (
    # -- decode: the serving universe (generate.DECODE_PROGRAMS) ----------
    ProgramDecl(
        "decode_batched", GENERATE, "_decode_batched_chunk_jit", "decode",
        static_args=_DECODE_STATICS, plan="always",
        goldens=("decode_batched_tiny", "decode_batched_int8",
                 "decode_batched_int4", "decode_batched_tp2",
                 "decode_batched_tp4"),
    ),
    ProgramDecl(
        "unified_prefill", GENERATE, "_decode_batched_prefill_chunk_jit",
        "decode",
        static_args=("model", "n_steps", "pchunk", "sample_cfg"),
        plan="per_bucket_unified",
        goldens=("decode_batched_prefill_tiny",),
    ),
    ProgramDecl(
        "spec_round", GENERATE, "_decode_batched_spec_round_jit", "decode",
        static_args=("model", "depth", "sample_cfg"), plan="spec",
        goldens=("decode_batched_spec_tiny",),
    ),
    ProgramDecl(
        "prefill", GENERATE, "_prefill_carry_jit", "decode",
        static_args=("model", "sample_cfg"), plan="never",
        note="exact-length host prefill: one compile per novel prompt "
             "length BY DESIGN, reachable only with prefill_buckets off — "
             "a bucketed replica never runs it, so the plan must not "
             "list it (phantom entries would break the warm-start "
             "'runs precisely these executables' contract)",
    ),
    ProgramDecl(
        "prefill_bucketed", GENERATE, "_prefill_carry_bucketed_jit",
        "decode",
        static_args=("model", "sample_cfg"), plan="per_bucket",
    ),
    # -- solo: the batch/CLI decode path ---------------------------------
    ProgramDecl(
        "generate", GENERATE, "_generate_jit", "solo",
        static_args=("model", "max_new_tokens", "sample_cfg"),
        keyspace="open",
        note="CLI batch generation: max_new_tokens is the invocation's "
             "token budget — one compile per run is the accepted cost; "
             "serving never calls this (the chunked programs exist "
             "precisely to avoid it)",
    ),
    ProgramDecl(
        "decode_chunk", GENERATE, "_decode_chunk_jit", "solo",
        static_args=_DECODE_STATICS, goldens=("decode_tiny",),
    ),
    # -- setup: one-shot construction-time programs ----------------------
    ProgramDecl(
        "quantize_decode_params", GENERATE, "quantize_for_decode", "setup",
        note="bare jax.jit over the whole-tree quantization: runs once "
             "per (model, params) at engine construction",
    ),
    ProgramDecl("slot_flags", BATCHING, "_slot_flags", "setup",
                note="per-chunk host readback probe; no static args"),
    ProgramDecl("spec_flags", BATCHING, "_spec_flags", "setup",
                note="speculative boundary readback probe; no static args"),
    ProgramDecl("insert_carry", BATCHING, "_insert_carry", "setup",
                note="slot admission row write; traced slot index — one "
                     "compile ever per engine shape"),
    ProgramDecl("stage_prompt_carry", BATCHING, "_stage_prompt_carry",
                "setup",
                note="in-scan admission staging; one compile per staged "
                     "buffer width"),
    ProgramDecl("stage_prefix_carry", BATCHING, "_stage_prefix_carry",
                "setup",
                note="prefix-cache-hit admission staging"),
    ProgramDecl("restart_prefill_row", BATCHING, "_restart_prefill_row",
                "setup",
                note="chaos-ladder rung 2 row rewind"),
    ProgramDecl("extract_carry", BATCHING, "_extract_carry", "setup",
                note="durable-session suspend row read"),
    # -- training: shard_map launchers (train-side key spaces) -----------
    ProgramDecl(
        "kernel_shard", "orion_tpu/parallel/kernel_shard.py",
        "shard_map_bh", "training", keyspace="open",
        note="manual bh shard of a Mosaic kernel call: keyed by the "
             "training mesh/config, not serving traffic",
    ),
    ProgramDecl(
        "sp_attention", "orion_tpu/parallel/sequence.py",
        "sp_linear_attention", "training", keyspace="open",
        note="sequence-parallel linear attention launcher (train mesh)",
    ),
    ProgramDecl(
        "ring_attention", "orion_tpu/parallel/ring.py", "ring_attention",
        "training", keyspace="open",
        note="ring attention launcher (train mesh)",
    ),
    ProgramDecl(
        "swa_halo_attention", "orion_tpu/parallel/ring.py",
        "swa_halo_attention", "training", keyspace="open",
        note="swa halo-exchange attention launcher (train mesh)",
    ),
    ProgramDecl(
        "pipeline_apply", "orion_tpu/parallel/pipeline.py",
        "pipeline_apply", "training", keyspace="open",
        note="pipeline-parallel stage launcher (train mesh)",
    ),
)


# The footprints Tier E and ``aot --decode --verify`` check the plan
# against, and the footprints the engine compile-count acceptance test
# drives traffic through (tests/test_aot.py). Values are chosen unique
# across the test suite so global jit-cache deltas are attributable.
# ``expect_programs`` is the DECLARED per-footprint program count —
# :func:`expected_decode_universe` must produce exactly that many rows.
CHECK_FOOTPRINTS: Tuple[Dict[str, Any], ...] = (
    {"slots": 3, "chunk": 6, "prefill_buckets": (12,), "prefill_chunk": 0,
     "qmode": "off", "tp": 1, "spec_depth": 0, "expect_programs": 2},
    {"slots": 5, "chunk": 7, "prefill_buckets": (12, 24),
     "prefill_chunk": 0, "qmode": "off", "tp": 1, "spec_depth": 0,
     "expect_programs": 3},
)


def expected_decode_universe(
    slots: int,
    chunk: int,
    prefill_buckets=(),
    prefill_chunk: int = 0,
    qmode: str = "off",
    tp: int = 1,
    spec_depth: int = 0,
    decls=None,
) -> List[Dict[str, Any]]:
    """The program universe a replica of this footprint compiles, computed
    from the DECLARATIONS (each decode row's ``plan`` applicability) —
    the independent side the plan-drift rule and ``aot --verify`` diff
    ``aot.decode_plan``'s inventory against. ``prefill_chunk`` here is
    the ALIGNED pchunk the engine actually compiles (decode_plan reports
    it as ``prefill_chunk_aligned``)."""
    tp = max(int(tp), 1)
    out: List[Dict[str, Any]] = []
    for d in decls if decls is not None else PROGRAMS:
        if d.section != "decode":
            continue
        if d.plan == "always":
            out.append({"kind": d.name, "slots": slots, "chunk": chunk,
                        "qmode": qmode, "tp": tp})
        elif d.plan == "per_bucket_unified" and int(prefill_chunk) > 0:
            for b in prefill_buckets or ():
                out.append({"kind": d.name, "slots": slots, "chunk": chunk,
                            "bucket": int(b),
                            "prefill_chunk": int(prefill_chunk),
                            "qmode": qmode, "tp": tp})
        elif d.plan == "per_bucket":
            for b in prefill_buckets or ():
                out.append({"kind": d.name, "bucket": int(b),
                            "qmode": qmode, "tp": tp})
        elif d.plan == "spec" and int(spec_depth) > 0:
            out.append({"kind": d.name, "slots": slots,
                        "spec_depth": int(spec_depth), "qmode": qmode,
                        "tp": tp})
        # "never"/"unplanned": not part of the planned universe
    return out


__all__ = [
    "ProgramDecl", "PROGRAMS", "FINITE_DOMAINS", "FINITE_ATTR_BASES",
    "CHECK_FOOTPRINTS", "expected_decode_universe", "GENERATE", "BATCHING",
]
