"""Tier C (part 1): SPMD collective auditor — trace, never execute.

Extends the Tier B trace-don't-execute approach to the sharded programs:
each target in :data:`SPMD_TARGETS` is traced with ``jax.make_jaxpr`` under
an abstract multi-device mesh (8 virtual CPU devices — the same mesh the
distributed tests run on; nothing executes, no weights materialize), every
communication collective in the jaxpr is extracted with its payload
dtype/bytes and loop scope, and the extraction is checked against the
budget the ``parallel/`` layer declares next to the code
(parallel/budgets.py). Check ids:

- ``spmd-unbudgeted-collective`` — a collective primitive the step's
  budget doesn't mention at all (e.g. a stray psum added to a shard_map
  body, or a manual collective leaking into the GSPMD-only train step).
- ``spmd-collective-count``      — more occurrences of a budgeted
  primitive than declared (a third ppermute per ring step doubles the
  critical-path ICI time without failing any CPU test).
- ``spmd-collective-dtype``     — payload dtype outside the declared set
  (an accidental f32 ring payload doubles ICI bytes silently).
- ``spmd-collective-in-scan``   — a collective the budget marks
  ``hoistable`` found inside a ``scan``/``while`` body, where it runs per
  step instead of once (e.g. the sp state all_gather accidentally pulled
  into a chunk loop).

Like Tier B, trace failures surface as ``audit-error`` findings, never
crashes. The extraction helpers take explicit jaxprs so tests can feed
deliberately-broken toys and doctored budgets.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Iterator, List, Optional, Tuple

from orion_tpu.analysis.findings import Finding
from orion_tpu.analysis.jaxpr_audit import AUDIT_ERROR, _where

RULE_UNBUDGETED = "spmd-unbudgeted-collective"
RULE_COUNT = "spmd-collective-count"
RULE_DTYPE = "spmd-collective-dtype"
RULE_IN_SCAN = "spmd-collective-in-scan"

ALL_SPMD_CHECKS = (RULE_UNBUDGETED, RULE_COUNT, RULE_DTYPE, RULE_IN_SCAN)

# the cross-device COMMUNICATION primitives (what budgets ration); unlike
# Tier B's COLLECTIVE_PRIMS this deliberately excludes axis_index — it
# moves no bytes
COMM_PRIMS = frozenset({
    "psum", "psum2", "all_gather", "all_to_all", "ppermute",
    "reduce_scatter", "psum_scatter", "pmax", "pmin", "pmean", "pgather",
    "pbroadcast",
})

_LOOP_PRIMS = frozenset({"scan", "while"})

N_VIRTUAL_DEVICES = 8


def ensure_cpu_devices(n: int = N_VIRTUAL_DEVICES) -> Optional[str]:
    """Make sure jax runs on >= n virtual CPU devices (the abstract mesh
    the audits trace under). Configures jax if its backends are not yet
    initialized (the CLI path — mirrors orion_tpu/aot.py); returns an
    error string (for an audit-error finding) if the process already
    initialized an unsuitable backend."""
    import jax

    try:
        from jax._src import xla_bridge

        initialized = xla_bridge.backends_are_initialized()
    except Exception:
        initialized = True  # can't tell: just inspect the live backend
    if not initialized:
        jax.config.update("jax_platforms", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            # the installed jax (0.4.x) predates jax_num_cpu_devices; the
            # XLA flag is honored as long as no backend has initialized
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
    # golden snapshots (analysis/snapshots.py) hash the compiled program;
    # partitionable threefry is what the test mesh uses — pin it so the
    # CLI and pytest produce byte-identical artifacts
    jax.config.update("jax_threefry_partitionable", True)
    if jax.default_backend() != "cpu" or jax.device_count() < n:
        return (
            f"spmd audit needs >= {n} virtual cpu devices but jax is "
            f"already initialized with {jax.device_count()} "
            f"{jax.default_backend()} device(s); run under "
            f"JAX_PLATFORMS=cpu with XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n}"
        )
    return None


# -- extraction ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CollectiveSite:
    prim: str
    dtypes: Tuple[str, ...]  # distinct dtypes over EVERY operand — a psum
    # of a (bf16, f32) tuple binds one eqn with two invars, and the f32
    # payload must not hide behind the first operand
    payload_bytes: int
    in_loop: bool  # lexically inside a scan/while body
    path: str
    line: int


def iter_eqns_scoped(jaxpr, in_loop: bool = False) -> Iterator[Tuple[Any, bool]]:
    """Every eqn with a flag for "inside a scan/while body", recursing into
    sub-jaxprs carried in eqn params (pjit/scan/cond/shard_map bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn, in_loop
        inner_loop = in_loop or eqn.primitive.name in _LOOP_PRIMS
        for v in eqn.params.values():
            for sub in v if isinstance(v, (list, tuple)) else (v,):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None:  # ClosedJaxpr
                    yield from iter_eqns_scoped(inner, inner_loop)
                elif hasattr(sub, "eqns"):  # raw Jaxpr
                    yield from iter_eqns_scoped(sub, inner_loop)


def _aval_bytes(aval) -> int:
    import numpy as np

    try:
        n = int(np.prod(aval.shape)) if aval.shape else 1
        return n * aval.dtype.itemsize
    except Exception:
        return 0


def extract_collectives(closed_jaxpr, target: str) -> List[CollectiveSite]:
    sites = []
    for eqn, in_loop in iter_eqns_scoped(closed_jaxpr.jaxpr):
        if eqn.primitive.name not in COMM_PRIMS:
            continue
        avals = [getattr(v, "aval", None) for v in eqn.invars]
        avals = [a for a in avals if a is not None]
        dtypes = tuple(sorted({str(a.dtype) for a in avals})) or ("?",)
        path, line = _where(eqn, target)
        sites.append(CollectiveSite(
            prim=eqn.primitive.name,
            dtypes=dtypes,
            payload_bytes=sum(_aval_bytes(a) for a in avals),
            in_loop=in_loop,
            path=path,
            line=line,
        ))
    return sites


# -- budget check -------------------------------------------------------------


def check_budget(
    sites: List[CollectiveSite], budget, target: str
) -> List[Finding]:
    """Check extracted collectives against a parallel/budgets.py
    ``StepBudget``. Pure — tests feed toy sites and doctored budgets."""
    findings: List[Finding] = []
    by_prim: Dict[str, List[CollectiveSite]] = {}
    for s in sites:
        by_prim.setdefault(s.prim, []).append(s)

    for prim, group in sorted(by_prim.items()):
        allow = budget.entry_for(prim)
        first = group[0]
        if allow is None:
            findings.append(Finding(
                RULE_UNBUDGETED, first.path, first.line,
                f"`{prim}` x{len(group)} in the {target} jaxpr but the "
                f"step's budget (parallel/budgets.py::BUDGETS[{target!r}]) "
                "declares no such collective — declare it (count/dtype/"
                "scope, with the cost reviewed) or remove it",
            ))
            continue
        if len(group) > allow.max_count:
            findings.append(Finding(
                RULE_COUNT, first.path, first.line,
                f"`{prim}` x{len(group)} in the {target} jaxpr exceeds the "
                f"budgeted {allow.max_count} — every extra occurrence is "
                "per-call ICI time; raise the budget only with the cost "
                "reviewed",
            ))
        for s in group:
            bad = [d for d in s.dtypes if d not in allow.dtypes]
            if bad:
                findings.append(Finding(
                    RULE_DTYPE, s.path, s.line,
                    f"`{prim}` payload dtype {'/'.join(bad)} "
                    f"({s.payload_bytes} B total) in the {target} jaxpr; "
                    f"budget allows {'/'.join(allow.dtypes)} — a wider "
                    "payload moves more ICI bytes with no parity-test "
                    "signal",
                ))
            if s.in_loop and allow.hoistable:
                findings.append(Finding(
                    RULE_IN_SCAN, s.path, s.line,
                    f"`{prim}` inside a scan/while body of the {target} "
                    "jaxpr but the budget marks it hoistable — inside the "
                    "loop it runs per step instead of once; hoist it out",
                ))
    return findings


# -- repo targets -------------------------------------------------------------


def _attn_inputs(dtype="bfloat16", b=2, h=2, t=64, d=8):
    import jax
    import jax.numpy as jnp

    sds = jax.ShapeDtypeStruct((b, h, t, d), jnp.dtype(dtype))
    return sds, sds, sds


def _sp_mesh(sp=4):
    from orion_tpu.parallel.mesh import MeshConfig, make_mesh

    return make_mesh(MeshConfig(dp=1, sp=sp))


def tiny_dp8_trainer():
    """ONE tiny bf16 dp=8 trainer + abstract batch shared by the budget
    audit (trace_train_step_dp) and the golden snapshot
    (snapshots._snap_train_tiny_dp8) — both must always describe the SAME
    compiled program."""
    import dataclasses as dc

    import jax
    import jax.numpy as jnp

    from orion_tpu.models.configs import get_config
    from orion_tpu.parallel.mesh import MeshConfig, make_mesh
    from orion_tpu.training.trainer import TrainConfig, Trainer

    cfg = TrainConfig(
        model=dc.replace(get_config("tiny"), dtype="bfloat16"),
        batch_size=8, seq_len=32, steps=10,
        mesh=MeshConfig(dp=N_VIRTUAL_DEVICES),
    )
    tr = Trainer(cfg, mesh=make_mesh(cfg.mesh), materialize=False)
    batch = jax.ShapeDtypeStruct(
        (cfg.batch_size, cfg.seq_len + 1), jnp.int32, sharding=tr.batch_shd
    )
    return tr, batch


def trace_train_step_dp():
    """The data-parallel train step under an explicit dp=8 mesh — the
    GSPMD path whose jaxpr must stay collective-free (jit inserts all
    communication from the shardings after tracing)."""
    import jax

    tr, batch = tiny_dp8_trainer()
    return jax.make_jaxpr(tr._train_step)(tr._abstract, batch)


def trace_sp_linear_attention():
    import jax

    from orion_tpu.parallel.sequence import sp_linear_attention

    mesh = _sp_mesh()
    q, k, v = _attn_inputs()
    return jax.make_jaxpr(
        lambda q, k, v: sp_linear_attention(q, k, v, mesh, backend="xla")
    )(q, k, v)


def _trace_ring(**kwargs):
    import jax

    from orion_tpu.parallel.ring import ring_attention

    mesh = _sp_mesh()
    q, k, v = _attn_inputs()
    return jax.make_jaxpr(
        lambda q, k, v: ring_attention(q, k, v, mesh, **kwargs)
    )(q, k, v)


def trace_ring_causal():
    return _trace_ring(causal=True)


def trace_ring_window():
    return _trace_ring(causal=True, window=16)


def trace_ring_striped():
    return _trace_ring(causal=True, striped=True)


def trace_swa_halo():
    """The halo form needs the flash kernel; interpret mode keeps the trace
    CPU-legal while the ppermute structure is identical to the real path."""
    import jax

    from orion_tpu.parallel.ring import swa_halo_attention

    mesh = _sp_mesh()
    q, k, v = _attn_inputs()
    return jax.make_jaxpr(
        lambda q, k, v: swa_halo_attention(
            q, k, v, mesh, window=24, backend="pallas_interpret"
        )
    )(q, k, v)


def tp_decode_pieces(tp: int = 2, slots: int = 8):
    """Shared fixtures for the tp decode traces AND the golden snapshots
    (snapshots._snap_decode_batched_tp): tiny model, tp=N mesh over the
    first N virtual devices, tp-sharded abstract params (the training
    rules), head-sharded abstract state, replicated per-slot vectors —
    budget audit and snapshot must always describe the SAME program, the
    one ``SlotEngine(mesh=...)`` serves. Returns
    (model, params, carry, rngs, vec, shardings) where ``shardings`` is
    the (param, state) NamedSharding pair for per-device accounting."""
    import jax
    import jax.numpy as jnp

    from orion_tpu.models.configs import get_config
    from orion_tpu.models.transformer import TransformerLM, init_decode_state
    from orion_tpu.parallel.decode import (
        decode_param_shardings,
        decode_state_shardings,
        serving_mesh,
    )

    cfg = get_config("tiny")
    model = TransformerLM(cfg)
    mesh = serving_mesh(tp)
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    sds = lambda l, s: jax.ShapeDtypeStruct(  # noqa: E731
        l.shape, l.dtype, sharding=s
    )
    prompt = jax.ShapeDtypeStruct((1, 8), jnp.int32)
    abstract = jax.eval_shape(model.init, jax.random.PRNGKey(0), prompt)
    p_shd = decode_param_shardings(abstract, mesh)
    params = jax.tree.map(sds, abstract, p_shd)
    states_abs = jax.eval_shape(lambda: init_decode_state(cfg, slots))
    st_shd = decode_state_shardings(states_abs, mesh)
    states = jax.tree.map(sds, states_abs, st_shd)
    vec = lambda dt: jax.ShapeDtypeStruct(  # noqa: E731
        (slots,), dt, sharding=rep
    )
    carry = (
        vec(jnp.int32), states, vec(jnp.int32), vec(jnp.int32),
        vec(jnp.bool_),
    )
    rngs = jax.ShapeDtypeStruct((slots, 2), jnp.uint32, sharding=rep)
    shardings = ((abstract, p_shd), (states_abs, st_shd), mesh)
    return model, params, carry, rngs, vec, shardings


def trace_decode_batched_tp():
    """The tp=2 slot-multiplexed decode chunk: like the GSPMD train step,
    the traced jaxpr must be collective-FREE (jit inserts the two
    per-block all-reduces from the shardings after tracing) — an
    explicit collective inside the decode scan would run per token."""
    import jax
    import jax.numpy as jnp

    from orion_tpu.generate import SampleConfig, _decode_batched_chunk_jit

    model, params, carry, rngs, vec, _ = tp_decode_pieces()
    return jax.make_jaxpr(
        _decode_batched_chunk_jit, static_argnums=(0, 5, 6)
    )(model, params, carry, rngs, vec(jnp.bool_), 8, SampleConfig())


def trace_decode_batched_prefill_tp():
    """The tp=2 unified in-scan prefill + decode program: staging and
    prompt pieces must stay jaxpr-collective-free too."""
    import jax
    import jax.numpy as jnp

    from orion_tpu.generate import (
        SampleConfig,
        _decode_batched_prefill_chunk_jit,
    )

    model, params, carry, rngs, vec, shardings = tp_decode_pieces()
    from jax.sharding import NamedSharding, PartitionSpec as P

    pbuf = jax.ShapeDtypeStruct(
        (8, 16), jnp.int32, sharding=NamedSharding(shardings[2], P())
    )
    return jax.make_jaxpr(
        _decode_batched_prefill_chunk_jit, static_argnums=(0, 8, 9, 10)
    )(
        model, params, carry, rngs, vec(jnp.bool_), pbuf, vec(jnp.int32),
        vec(jnp.int32), 8, 16, SampleConfig(),
    )


def trace_pipeline_lm_step():
    """The pp=2 trainer step (fwd+bwd): stage-rotation ppermutes inside the
    GPipe scan plus the loop-invariant psums its transposes generate."""
    import dataclasses as dc

    import jax
    import jax.numpy as jnp

    from orion_tpu.models.configs import get_config
    from orion_tpu.parallel.mesh import MeshConfig, make_mesh
    from orion_tpu.training.trainer import TrainConfig, Trainer

    cfg = TrainConfig(
        model=dc.replace(get_config("tiny"), dtype="bfloat16"),
        batch_size=4, seq_len=32, steps=10, mesh=MeshConfig(dp=1, pp=2),
    )
    tr = Trainer(cfg, mesh=make_mesh(cfg.mesh), materialize=False)
    batch = jax.ShapeDtypeStruct((cfg.batch_size, cfg.seq_len + 1), jnp.int32)
    return jax.make_jaxpr(tr._train_step)(tr._abstract, batch)


# trace-target name -> zero-arg tracer; keys must match
# parallel/budgets.py::BUDGETS (tested in tests/test_analysis.py)
SPMD_TARGETS = {
    "train_step_dp": trace_train_step_dp,
    "sp_linear_attention": trace_sp_linear_attention,
    "ring_attention_causal": trace_ring_causal,
    "ring_attention_window": trace_ring_window,
    "ring_attention_striped": trace_ring_striped,
    "swa_halo_attention": trace_swa_halo,
    "pipeline_lm_step": trace_pipeline_lm_step,
    "decode_batched_tp": trace_decode_batched_tp,
    "decode_batched_prefill_tp": trace_decode_batched_prefill_tp,
}


def audit_spmd(budgets: Optional[Dict[str, Any]] = None) -> List[Finding]:
    """Trace every SPMD target and check it against its declared budget.
    ``budgets`` overrides parallel/budgets.py::BUDGETS (tests inject
    doctored budgets to prove violations gate)."""
    err = ensure_cpu_devices()
    if err is not None:
        return [Finding(AUDIT_ERROR, "<spmd>", 0, err)]
    if budgets is None:
        from orion_tpu.parallel.budgets import BUDGETS as budgets

    findings: List[Finding] = []
    for name, tracer in SPMD_TARGETS.items():
        budget = budgets.get(name)
        if budget is None:
            findings.append(Finding(
                AUDIT_ERROR, f"<spmd:{name}>", 0,
                f"no budget declared for SPMD target {name!r} in "
                "parallel/budgets.py::BUDGETS",
            ))
            continue
        try:
            sites = extract_collectives(tracer(), name)
        except Exception as e:  # noqa: BLE001 - surfaced as finding, not crash
            findings.append(Finding(
                AUDIT_ERROR, f"<spmd:{name}>", 0,
                f"tracing {name} failed: {type(e).__name__}: {e}",
            ))
            continue
        findings.extend(check_budget(sites, budget, name))
    return findings


__all__ = [
    "audit_spmd", "check_budget", "extract_collectives", "iter_eqns_scoped",
    "ensure_cpu_devices", "CollectiveSite", "SPMD_TARGETS",
    "ALL_SPMD_CHECKS", "RULE_UNBUDGETED", "RULE_COUNT", "RULE_DTYPE",
    "RULE_IN_SCAN", "N_VIRTUAL_DEVICES",
]
