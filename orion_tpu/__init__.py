"""orion-tpu: a TPU-native linear-attention transformer framework.

A ground-up JAX/XLA/Pallas implementation of the capabilities of
`angeloskath/orion` (reference spec: /root/repo/BASELINE.json north_star —
the reference checkout itself was never mounted, see SURVEY.md §0):

- causal linear attention in its three equivalent forms (parallel O(T^2)
  eager reference, chunked kv-cumsum recurrence for training, O(1)-state
  recurrent form for decoding), with Pallas TPU kernels behind a
  ``backend=`` dispatch,
- softmax and sliding-window attention (flash-style Pallas kernels) for the
  LRA configs and the hybrid model family,
- ``train`` / ``generate`` entrypoints,
- data/fsdp/tensor/sequence/pipeline/expert parallelism over a
  `jax.sharding.Mesh` with XLA collectives over ICI/DCN (replacing the
  reference's NCCL wrapper), including routed-expert (MoE) models.
"""

__version__ = "0.1.0"

from orion_tpu import ops

# Lazy top-level API: heavy submodules (training pulls optax/orbax, generate
# pulls models) load on first use, keeping `import orion_tpu` light.
_LAZY = {
    "train": ("orion_tpu.train", "train"),
    "TrainConfig": ("orion_tpu.training.trainer", "TrainConfig"),
    "Trainer": ("orion_tpu.training.trainer", "Trainer"),
    "generate": ("orion_tpu.generate", "generate"),
    "SampleConfig": ("orion_tpu.generate", "SampleConfig"),
    "TransformerLM": ("orion_tpu.models.transformer", "TransformerLM"),
    "LRAClassifier": ("orion_tpu.models.classifier", "LRAClassifier"),
    "ModelConfig": ("orion_tpu.models.configs", "ModelConfig"),
    "get_config": ("orion_tpu.models.configs", "get_config"),
    "MoEMLP": ("orion_tpu.models.moe", "MoEMLP"),
    "MeshConfig": ("orion_tpu.parallel.mesh", "MeshConfig"),
    "make_mesh": ("orion_tpu.parallel.mesh", "make_mesh"),
    "register_feature_map": (
        "orion_tpu.ops.feature_maps", "register_feature_map",
    ),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module 'orion_tpu' has no attribute {name!r}")


__all__ = ["ops", "__version__", *_LAZY]
