"""orion-tpu: a TPU-native linear-attention transformer framework.

A ground-up JAX/XLA/Pallas implementation of the capabilities of
`angeloskath/orion` (reference spec: /root/repo/BASELINE.json north_star —
the reference checkout itself was never mounted, see SURVEY.md §0):

- causal linear attention in its three equivalent forms (parallel O(T^2)
  eager reference, chunked kv-cumsum recurrence for training, O(1)-state
  recurrent form for decoding), with Pallas TPU kernels behind a
  ``backend=`` dispatch,
- softmax and sliding-window attention (flash-style Pallas kernels) for the
  LRA configs and the hybrid model family,
- ``train`` / ``generate`` entrypoints,
- data/fsdp/tensor/sequence parallelism over a `jax.sharding.Mesh` with XLA
  collectives over ICI/DCN (replacing the reference's NCCL wrapper).
"""

__version__ = "0.1.0"

from orion_tpu import ops

__all__ = ["ops", "__version__"]
