"""Kernel-level micro-benchmarks: Pallas kernels vs their XLA twins
(VERDICT r1 item 3 — "prove the Pallas kernels beat XLA somewhere real").

Sweeps causal linear attention (fused Pallas kernel vs XLA chunked scan)
and softmax attention (Pallas flash vs XLA masked-dense) across sequence
lengths at a fixed per-layer operating shape, forward and forward+backward.
Used by ``bench.py --kernels`` on the real chip; results feed the
per-shape "auto" backend heuristic in ops/dispatch.py.

Timing note: dispatch to the chip rides a network relay (~ms RTT), so each
measurement enqueues ``iters`` async calls and then forces a small host
readback of the last output. ``jax.block_until_ready`` alone is NOT a real
barrier through the relay (measured: chained 8192³ matmuls "complete" in
0.02 ms); only a device→host transfer forces execution.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np


def _sync(out) -> None:
    """Force real completion: read a few elements back to the host."""
    leaf = jax.tree.leaves(out)[0]
    np.asarray(jax.device_get(leaf.ravel()[:8]))


def _time_fn(fn: Callable, args, iters: int = 20, warmup: int = 2) -> float:
    """Median-of-3 wall time (ms) of ``iters`` back-to-back dispatches."""
    out = None
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        _sync(out)
        times.append((time.perf_counter() - t0) / iters * 1000)
    return sorted(times)[1]


def _qkv(b, h, t, d, dtype=jnp.bfloat16, featurized=True):
    ks = jax.random.split(jax.random.key(0), 3)
    if featurized:  # post-feature-map positives, like the model's linear layers
        q = jax.nn.elu(jax.random.normal(ks[0], (b, h, t, d), dtype)) + 1
        k = jax.nn.elu(jax.random.normal(ks[1], (b, h, t, d), dtype)) + 1
    else:
        q = jax.random.normal(ks[0], (b, h, t, d), dtype)
        k = jax.random.normal(ks[1], (b, h, t, d), dtype)
    v = jax.random.normal(ks[2], (b, h, t, d), dtype)
    return q, k, v


def bench_linear_attention(shapes=None, iters: int = 20) -> List[Dict]:
    """Fused normalized linear attention: Pallas kernel vs XLA chunked."""
    from orion_tpu.ops.linear_attention import linear_attention

    if shapes is None:
        # fixed token budget b*t; h/d = lm_1b3 layer geometry
        shapes = [(16, 16, 2048, 128), (4, 16, 8192, 128), (2, 16, 16384, 128),
                  (1, 16, 32768, 128)]
    rows = []
    for b, h, t, d in shapes:
        q, k, v = _qkv(b, h, t, d)
        row = {"op": "linear_attention", "b": b, "h": h, "t": t, "d": d}
        for backend in ("xla", "pallas"):
            fwd = jax.jit(partial(linear_attention, backend=backend))

            def loss(q, k, v, _f=fwd):
                return _f(q, k, v).astype(jnp.float32).sum()

            fb = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            row[f"{backend}_fwd_ms"] = round(_time_fn(fwd, (q, k, v), iters), 3)
            row[f"{backend}_fwdbwd_ms"] = round(_time_fn(fb, (q, k, v), iters), 3)
        row["speedup_fwd"] = round(row["xla_fwd_ms"] / row["pallas_fwd_ms"], 3)
        row["speedup_fwdbwd"] = round(
            row["xla_fwdbwd_ms"] / row["pallas_fwdbwd_ms"], 3
        )
        rows.append(row)
    return rows


def _bench_softmax_family(
    op_name: str, window, shapes, iters: int
) -> List[Dict]:
    """Shared harness for the softmax-attention family: Pallas flash vs
    XLA masked-dense, optionally windowed."""
    from orion_tpu.ops.softmax_attention import softmax_attention

    if shapes is None:
        shapes = [(16, 16, 2048, 128), (4, 16, 8192, 128), (2, 16, 16384, 128)]
    rows = []
    for b, h, t, d in shapes:
        q, k, v = _qkv(b, h, t, d, featurized=False)
        row = {"op": op_name, "b": b, "h": h, "t": t, "d": d}
        if window is not None:
            row["window"] = window
        for backend in ("xla", "pallas"):
            fwd = jax.jit(
                partial(
                    softmax_attention, causal=True, window=window,
                    backend=backend,
                )
            )

            def loss(q, k, v, _f=fwd):
                return _f(q, k, v).astype(jnp.float32).sum()

            fb = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            try:
                row[f"{backend}_fwd_ms"] = round(_time_fn(fwd, (q, k, v), iters), 3)
                row[f"{backend}_fwdbwd_ms"] = round(
                    _time_fn(fb, (q, k, v), iters), 3
                )
            except Exception as e:  # dense T×T OOMs at long T
                row[f"{backend}_fwd_ms"] = None
                row[f"{backend}_fwdbwd_ms"] = None
                row[f"{backend}_error"] = str(e).splitlines()[0][:120]
        if row.get("xla_fwd_ms") and row.get("pallas_fwd_ms"):
            row["speedup_fwd"] = round(row["xla_fwd_ms"] / row["pallas_fwd_ms"], 3)
            row["speedup_fwdbwd"] = round(
                row["xla_fwdbwd_ms"] / row["pallas_fwdbwd_ms"], 3
            )
        rows.append(row)
    return rows


def bench_softmax_attention(shapes=None, iters: int = 20) -> List[Dict]:
    """Causal softmax attention: Pallas flash vs XLA masked-dense."""
    return _bench_softmax_family("softmax_attention", None, shapes, iters)


def bench_swa_attention(shapes=None, window: int = 1024, iters: int = 20) -> List[Dict]:
    """Sliding-window softmax (the 7B hybrid's dominant layer type,
    BASELINE.json config #5): Pallas flash with structural tile skipping
    vs XLA masked-dense. The flash path's cost is O(T·W); the dense path
    is O(T²) regardless of the window."""
    return _bench_softmax_family("swa_attention", window, shapes, iters)


def run_all(iters: int = 20) -> List[Dict]:
    return (
        bench_linear_attention(iters=iters)
        + bench_softmax_attention(iters=iters)
        + bench_swa_attention(iters=iters)
    )


if __name__ == "__main__":
    import json

    for r in run_all():
        print(json.dumps(r), flush=True)
