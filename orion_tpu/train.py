"""`python -m orion_tpu.train` — the training entrypoint (SURVEY.md T1).

TPU-native counterpart of the reference's `orion.train` (BASELINE.json;
reference checkout never mounted — SURVEY.md §0). Library use:

    from orion_tpu.train import train
    state, metrics = train(TrainConfig(model=get_config("tiny"), steps=100),
                           data="synthetic")

CLI:

    python -m orion_tpu.train --config tiny --steps 1000 --data synthetic \
        --set lr=1e-3 --set model.n_layers=4 --ckpt-dir /tmp/ckpt
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import sys
from typing import Optional, Tuple

from orion_tpu.models.configs import get_config
from orion_tpu.parallel.mesh import MeshConfig, initialize_distributed
from orion_tpu.resilience.preempt import PreemptionGuard
from orion_tpu.resilience.watchdog import Watchdog
from orion_tpu.training.checkpoint import Checkpointer
from orion_tpu.training.data import DataLoader, make_dataset
from orion_tpu.training.metrics import MetricsLogger
from orion_tpu.training.trainer import TrainConfig, Trainer


def train(
    cfg: TrainConfig,
    data: str = "synthetic",
    eval_data: Optional[str] = None,
    log_path: Optional[str] = None,
    resume: bool = True,
    metrics_path: Optional[str] = None,
) -> Tuple[object, dict]:
    """Build everything, optionally resume, run to cfg.steps. Returns
    (final TrainState, last metrics dict)."""
    # config errors before the expensive part: Trainer materializes multi-GB
    # state and the loader spawns its prefetch thread
    if eval_data and not cfg.eval_every:
        raise ValueError(
            "eval_data given but eval_every == 0 — the held-out split "
            "would silently never be evaluated; set eval_every > 0 "
            "(CLI: --eval-every N)"
        )
    trainer = Trainer(cfg)
    ckpt = None
    start = 0
    if cfg.ckpt_dir:
        ckpt = Checkpointer(
            cfg.ckpt_dir, max_to_keep=cfg.ckpt_keep, save_every=cfg.ckpt_every
        )
        if resume and ckpt.latest_step is not None:
            start = trainer.restore(ckpt)
            print(f"resumed from step {start}", file=sys.stderr)

    dataset = make_dataset(data, cfg.seq_len, cfg.model.vocab_size)
    assert dataset.vocab_size <= cfg.model.vocab_size, (
        f"data vocab {dataset.vocab_size} > model vocab {cfg.model.vocab_size}"
    )
    loader = DataLoader(
        dataset,
        cfg.batch_size,
        seed=cfg.seed,
        start_step=start,
        sharding=trainer.batch_shd,
        stall_timeout=cfg.step_timeout if cfg.step_timeout > 0 else None,
    )
    logger = MetricsLogger(log_path)
    if cfg.ckpt_dir:
        # the run directory doubles as the black box's dump target: a
        # preemption or nan-halt leaves flight-*.json beside the
        # checkpoints it force-saved (obs/flight.py)
        import os as _os

        from orion_tpu.obs import flight as _flight

        _flight.configure(dump_dir=_os.path.join(cfg.ckpt_dir, "flight"))
    eval_factory = None
    if cfg.eval_every:
        # a real held-out split when given (--eval-data val.bin); otherwise
        # a disjoint-seed stream over the training data
        eval_ds = (
            make_dataset(eval_data, cfg.seq_len, cfg.model.vocab_size)
            if eval_data
            else dataset
        )
        assert eval_ds.vocab_size <= cfg.model.vocab_size, (
            f"eval data vocab {eval_ds.vocab_size} > model vocab "
            f"{cfg.model.vocab_size}"
        )

        def eval_factory(step, _ds=eval_ds):
            # batches a pure function of the TRAIN step — a resumed run
            # re-evaluates any step's eval on the exact same batches. A
            # short-lived DataLoader keeps the prefetch overlap AND the
            # multi-host make_array_from_callback path (data.py P7/P11)
            # the sampling math alone would lose.
            base = 10_000_000 + step * cfg.eval_batches
            loader = DataLoader(
                _ds, cfg.batch_size, seed=cfg.seed + 1, start_step=base,
                sharding=trainer.batch_shd,
                # eval reads get the same stall budget as train reads — a
                # dead mount under --eval-data must raise a diagnosable
                # StallError, not hang the (watchdog-disarmed) eval pass
                stall_timeout=cfg.step_timeout if cfg.step_timeout > 0 else None,
            )

            def gen():
                try:
                    it = iter(loader)
                    for j in range(cfg.eval_batches):
                        batch = next(it)
                        if j == cfg.eval_batches - 1:
                            loader.close()  # last batch out; stop the thread
                        yield batch
                finally:
                    loader.close()  # safety if the consumer stops early

            return gen()
    # resilience wiring (resilience/): preempt_grace > 0 installs the
    # SIGTERM/SIGINT graceful-stop guard for the duration of the run;
    # step_timeout > 0 arms the hang watchdog (the loader's stall detector
    # is wired above with the same budget)
    from orion_tpu.obs import flight as _fl

    guard_cm = (
        PreemptionGuard(
            cfg.preempt_grace,
            # signal-context tap: the black box records the signal the
            # instant it lands (lock-free append — the handler runs
            # between two arbitrary bytecodes), not just the boundary
            # where the trainer later acts on it
            on_stop=lambda signum: _fl.recorder().record_signal_safe(
                "preempt_signal", signum=signum
            ),
        )
        if cfg.preempt_grace > 0
        else contextlib.nullcontext()
    )
    watchdog = Watchdog(cfg.step_timeout) if cfg.step_timeout > 0 else None
    try:
        with guard_cm as guard:
            last = trainer.train(
                iter(loader), logger=logger, ckpt=ckpt,
                eval_factory=eval_factory, preempt=guard, watchdog=watchdog,
            )
        if trainer.preempted_at is not None:
            note = (
                "emergency checkpoint saved; rerun with the same "
                "--ckpt-dir to resume"
                if ckpt is not None
                else "NO checkpointer configured — progress since the last "
                     "save is lost (set --ckpt-dir)"
            )
            print(
                f"preempted at step {trainer.preempted_at}: {note}",
                file=sys.stderr,
            )
        elif ckpt is not None:
            ckpt.maybe_save(int(trainer.state.step), trainer.state, force=True)
    finally:
        if watchdog is not None:
            watchdog.close()
        loader.close()
        if metrics_path:
            # final scrape on every exit path (same contract as the
            # serving CLI's on-drain dump): Prometheus text + .json
            try:
                logger.dump(metrics_path)
            except OSError as e:
                print(f"metrics dump failed: {e}", file=sys.stderr)
        logger.close()
        if ckpt is not None:
            # close() waits for any in-flight async save, INCLUDING on the
            # exception path — a raise mid-train must not abandon a
            # half-written step (the manifest/fallback machinery handles
            # torn writes, but not leaking the writer)
            ckpt.close()
    return trainer.state, last


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("orion_tpu.train")
    p.add_argument("--config", default="tiny", help="named model config")
    p.add_argument("--data", default="synthetic", help="'synthetic' or token-bin path")
    p.add_argument("--eval-data", default=None,
                   help="held-out token-bin path for eval (default: train data)")
    p.add_argument("--eval-every", type=int, default=0,
                   help="eval cadence in steps (0 = no interleaved eval)")
    p.add_argument("--steps", type=int, default=1000)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--log-path", default=None)
    p.add_argument("--metrics-path", default=None,
                   help="Prometheus-text metrics exposition file "
                        "(+ .json sibling), written on exit — the same "
                        "registry format the serving/fleet CLIs expose")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--preempt-grace", type=float, default=10.0,
        help="seconds budgeted for the emergency checkpoint on SIGTERM/"
             "SIGINT (graceful stop at the next step boundary); 0 disables "
             "the signal handlers",
    )
    p.add_argument(
        "--step-timeout", type=float, default=0.0,
        help="hang watchdog: raise StallError if no step completes (or no "
             "data batch arrives) for this many seconds — must exceed jit "
             "compile + one step; 0 disables",
    )
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline stages (depth-homogeneous models)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel axis (MoE configs, model.n_experts>0)")
    p.add_argument("--distributed", action="store_true", help="multi-host init")
    p.add_argument(
        "--set", action="append", default=[], metavar="KEY=VALUE",
        help="dotted TrainConfig override, e.g. --set model.n_layers=4",
    )
    p.add_argument("--config-json", default=None, help="JSON override file")
    return p


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    from orion_tpu.utils.cache import enable_compile_cache

    enable_compile_cache()
    if args.distributed:
        initialize_distributed()
    from orion_tpu.utils.config import apply_overrides, load_json_overrides

    cfg = TrainConfig(
        model=get_config(args.config),
        steps=args.steps,
        batch_size=args.batch_size,
        seq_len=args.seq_len,
        lr=args.lr,
        seed=args.seed,
        eval_every=args.eval_every,
        ckpt_dir=args.ckpt_dir,
        preempt_grace=args.preempt_grace,
        step_timeout=args.step_timeout,
        mesh=MeshConfig(dp=args.dp, fsdp=args.fsdp, tp=args.tp, sp=args.sp,
                        pp=args.pp, ep=args.ep),
    )
    if args.config_json:
        cfg = apply_overrides(cfg, load_json_overrides(args.config_json))
    from orion_tpu.utils.config import parse_set_overrides

    overrides = parse_set_overrides(args.set)
    if overrides:
        cfg = apply_overrides(cfg, overrides)
    if cfg.seq_len >= cfg.model.max_seq_len:
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, max_seq_len=cfg.seq_len + 1)
        )
    _, last = train(
        cfg, data=args.data, eval_data=args.eval_data,
        log_path=args.log_path, metrics_path=args.metrics_path,
    )
    print({k: round(v, 5) for k, v in last.items()})
    return 0


if __name__ == "__main__":
    sys.exit(main())
