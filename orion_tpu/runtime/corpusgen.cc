// Native synthetic-corpus generator (SURVEY.md T5/N-family; VERDICT r4 #2).
//
// The environment has no network egress, so a pretraining-scale corpus
// (100M+ tokens — ~30x the worked example) must be synthesized locally.
// This samples an interpolated trigram/bigram/unigram Markov source fitted
// on an existing token-bin corpus: locally realistic token statistics, an
// entropy floor set by the interpolation weights (so held-out perplexity
// falls smoothly for an entire endurance run instead of bottoming out on a
// memorized 3.7M-token loop), and no possibility of verbatim memorization
// at the corpus level because the sampled stream never repeats.
//
// Determinism contract (mirrored bit-for-bit by the Python twin,
// orion_tpu/training/corpusgen.py): draw k of a run is
// splitmix64(seed + k) — the same finalizer the data loader uses — and
// each output token consumes exactly two draws (branch pick, successor
// pick). Successor lists are ordered by corpus position (stable counting
// sort here, stable argsort in Python), so `list[r % len]` agrees.
//
// Build: runtime/build.sh -> liborion_runtime.so (plain C ABI, ctypes).

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

constexpr uint64_t kGamma = 0x9E3779B97F4A7C15ull;
constexpr uint64_t kM1 = 0xBF58476D1CE4E5B9ull;
constexpr uint64_t kM2 = 0x94D049BB133111EBull;

inline uint64_t splitmix64(uint64_t x) {
  uint64_t z = x + kGamma;
  z = (z ^ (z >> 30)) * kM1;
  z = (z ^ (z >> 27)) * kM2;
  return z ^ (z >> 31);
}

// draw in [0, 1): top 53 bits, exactly what numpy's (r >> 11) * 2**-53 does
inline double unit(uint64_t r) {
  return static_cast<double>(r >> 11) * (1.0 / 9007199254740992.0);
}

struct Model {
  const uint16_t* corpus = nullptr;
  int64_t n = 0;
  // bigram CSR: dense offsets over the 2^16 token space
  std::vector<int64_t> bi_off;      // [65537]
  std::vector<uint16_t> bi_succ;    // [n-1], corpus-position order
  // trigram CSR: sorted unique (a<<16|b) codes + offsets + successors
  std::vector<uint32_t> tri_code;   // [n_ctx]
  std::vector<int64_t> tri_off;     // [n_ctx+1]
  std::vector<uint16_t> tri_succ;   // [n-2], corpus-position order
};

}  // namespace

extern "C" {

// Fit the interpolated Markov model on corpus[0..n). Returns a handle.
void* orion_corpusgen_fit(const uint16_t* corpus, int64_t n) {
  if (n < 3) return nullptr;
  auto* m = new Model;
  m->corpus = corpus;
  m->n = n;

  // bigram: counting sort by context token (stable: ascending i)
  std::vector<int64_t> cnt(65536 + 1, 0);
  for (int64_t i = 0; i + 1 < n; ++i) cnt[corpus[i]]++;
  m->bi_off.assign(65537, 0);
  for (int t = 0; t < 65536; ++t) m->bi_off[t + 1] = m->bi_off[t] + cnt[t];
  m->bi_succ.resize(n - 1);
  {
    std::vector<int64_t> cur(m->bi_off.begin(), m->bi_off.end() - 1);
    for (int64_t i = 0; i + 1 < n; ++i)
      m->bi_succ[cur[corpus[i]]++] = corpus[i + 1];
  }

  // trigram: stable sort of (code, i), then unique codes + CSR
  std::vector<std::pair<uint32_t, int64_t>> entries;
  entries.reserve(n - 2);
  for (int64_t i = 0; i + 2 < n; ++i) {
    uint32_t code = (static_cast<uint32_t>(corpus[i]) << 16) | corpus[i + 1];
    entries.emplace_back(code, i);
  }
  std::stable_sort(entries.begin(), entries.end(),
                   [](const auto& x, const auto& y) {
                     return x.first < y.first;
                   });
  m->tri_succ.resize(entries.size());
  for (size_t j = 0; j < entries.size(); ++j) {
    m->tri_succ[j] = corpus[entries[j].second + 2];
    if (j == 0 || entries[j].first != entries[j - 1].first) {
      m->tri_code.push_back(entries[j].first);
      m->tri_off.push_back(static_cast<int64_t>(j));
    }
  }
  m->tri_off.push_back(static_cast<int64_t>(entries.size()));
  return m;
}

// Sample n_out tokens into out. Each token: draw r0 picks the branch
// (unigram if u < p_uni, else bigram if u < p_uni + p_bi, else trigram,
// falling back tri->bi->uni when a context is unseen), draw r1 picks the
// successor by index. State seeds from draw pair k=0 (start bigram).
void orion_corpusgen_sample(void* handle, uint64_t seed, double p_uni,
                            double p_bi, int64_t n_out, uint16_t* out) {
  auto* m = static_cast<Model*>(handle);
  // Decorrelate the draw stream's ORIGIN from the user seed: with a raw
  // counter stream splitmix64(seed + k), seeds i and i+2 yield the same
  // draws shifted by one token pair — adjacent-seeded "shards" coalesce
  // into verbatim copies within ~100 tokens (caught in r5 review). One
  // finalizer pass scatters origins uniformly over 2^64, making stream
  // overlap a ~2n/2^64 probability event instead of a certainty.
  seed = splitmix64(seed);
  uint64_t k = 0;
  uint64_t r = splitmix64(seed + k++);
  int64_t s = static_cast<int64_t>(r % static_cast<uint64_t>(m->n - 1));
  uint16_t a = m->corpus[s], b = m->corpus[s + 1];
  (void)splitmix64(seed + k++);  // keep pairs aligned (draw 1 unused)

  for (int64_t j = 0; j < n_out; ++j) {
    double u = unit(splitmix64(seed + k++));
    uint64_t r1 = splitmix64(seed + k++);
    int order = u < p_uni ? 1 : (u < p_uni + p_bi ? 2 : 3);
    uint16_t nxt;
    if (order == 3) {
      uint32_t code = (static_cast<uint32_t>(a) << 16) | b;
      auto it = std::lower_bound(m->tri_code.begin(), m->tri_code.end(), code);
      if (it != m->tri_code.end() && *it == code) {
        size_t idx = it - m->tri_code.begin();
        int64_t lo = m->tri_off[idx], hi = m->tri_off[idx + 1];
        nxt = m->tri_succ[lo + static_cast<int64_t>(
                                   r1 % static_cast<uint64_t>(hi - lo))];
      } else {
        order = 2;  // unseen trigram context (possible after a jump)
      }
    }
    if (order == 2) {
      int64_t lo = m->bi_off[b], hi = m->bi_off[b + 1];
      if (hi > lo) {
        nxt = m->bi_succ[lo + static_cast<int64_t>(
                                  r1 % static_cast<uint64_t>(hi - lo))];
      } else {
        order = 1;  // token only ever appeared corpus-final
      }
    }
    if (order == 1) {
      nxt = m->corpus[r1 % static_cast<uint64_t>(m->n)];
    }
    out[j] = nxt;
    a = b;
    b = nxt;
  }
}

void orion_corpusgen_destroy(void* handle) {
  delete static_cast<Model*>(handle);
}

}  // extern "C"
