// Native host-side data loader for orion_tpu (SURVEY.md N1).
//
// The reference keeps its dataset/loader in the C++/CUDA extension layer
// (BASELINE.json; reference checkout never mounted — SURVEY.md §0). On TPU
// the device-side story belongs to XLA, so the native layer's job is the
// host hot path: mmap the token-bin file, gather shuffled windows into a
// pinned int32 batch buffer with a worker-thread pool, and hand numpy a
// ready array through ctypes (which releases the GIL for the whole call).
//
// Determinism contract: window starts are splitmix64(seed ^ step*C1 ^
// row*C2) % n_windows — bit-for-bit the same stream as the Python fallback
// (orion_tpu/training/data.py::window_starts), so checkpoints resume onto
// identical batches regardless of which loader produced them.
//
// Build: runtime/build.sh -> liborion_runtime.so (plain C ABI for ctypes).

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint64_t kGamma = 0x9E3779B97F4A7C15ull;
constexpr uint64_t kM1 = 0xBF58476D1CE4E5B9ull;
constexpr uint64_t kM2 = 0x94D049BB133111EBull;
constexpr uint64_t kStepMix = 0xD1B54A32D192ED03ull;
constexpr uint64_t kRowMix = 0x8CB92BA72F3D8DD7ull;

inline uint64_t splitmix64(uint64_t x) {
  uint64_t z = x + kGamma;
  z = (z ^ (z >> 30)) * kM1;
  z = (z ^ (z >> 27)) * kM2;
  return z ^ (z >> 31);
}

struct Loader {
  const uint8_t* data = nullptr;
  size_t file_bytes = 0;
  int64_t n_tokens = 0;
  int itemsize = 2;  // uint16 or uint32 token files
  int64_t seq_len = 0;
  int64_t n_windows = 0;
  int fd = -1;
};

template <typename T>
void gather_rows(const Loader* L, const uint64_t seed, const uint64_t step,
                 int64_t row_begin, int64_t row_end, int32_t* out) {
  const T* toks = reinterpret_cast<const T*>(L->data);
  const int64_t w = L->seq_len + 1;
  for (int64_t r = row_begin; r < row_end; ++r) {
    uint64_t x = seed ^ (step * kStepMix) ^ (static_cast<uint64_t>(r) * kRowMix);
    int64_t start =
        static_cast<int64_t>(splitmix64(x) % static_cast<uint64_t>(L->n_windows));
    int32_t* dst = out + r * w;
    const T* src = toks + start;
    for (int64_t j = 0; j < w; ++j) dst[j] = static_cast<int32_t>(src[j]);
  }
}

template <typename T>
void gather_explicit(const Loader* L, const int64_t* starts, int64_t row_begin,
                     int64_t row_end, int32_t* out) {
  const T* toks = reinterpret_cast<const T*>(L->data);
  const int64_t w = L->seq_len + 1;
  for (int64_t r = row_begin; r < row_end; ++r) {
    int32_t* dst = out + r * w;
    const T* src = toks + starts[r];
    for (int64_t j = 0; j < w; ++j) dst[j] = static_cast<int32_t>(src[j]);
  }
}

}  // namespace

extern "C" {

// Returns an opaque handle, or nullptr on failure.
void* orion_loader_open(const char* path, int64_t seq_len, int itemsize) {
  if (itemsize != 2 && itemsize != 4) return nullptr;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size <= 0) {
    ::close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  madvise(map, st.st_size, MADV_RANDOM);
  auto* L = new Loader;
  L->data = static_cast<const uint8_t*>(map);
  L->file_bytes = st.st_size;
  L->itemsize = itemsize;
  L->n_tokens = st.st_size / itemsize;
  L->seq_len = seq_len;
  L->n_windows = L->n_tokens - seq_len - 1;
  L->fd = fd;
  if (L->n_windows <= 0) {
    munmap(map, st.st_size);
    ::close(fd);
    delete L;
    return nullptr;
  }
  return L;
}

int64_t orion_loader_n_tokens(void* handle) {
  return static_cast<Loader*>(handle)->n_tokens;
}

// Fill out[batch, seq_len+1] (int32, row-major). Multi-threaded gather.
void orion_loader_batch(void* handle, uint64_t seed, uint64_t step,
                        int64_t batch, int32_t* out, int n_threads) {
  auto* L = static_cast<Loader*>(handle);
  if (n_threads < 1) n_threads = 1;
  if (n_threads > batch) n_threads = static_cast<int>(batch);
  auto run = [&](int64_t lo, int64_t hi) {
    if (L->itemsize == 2) {
      gather_rows<uint16_t>(L, seed, step, lo, hi, out);
    } else {
      gather_rows<uint32_t>(L, seed, step, lo, hi, out);
    }
  };
  if (n_threads == 1) {
    run(0, batch);
    return;
  }
  std::vector<std::thread> ts;
  int64_t per = (batch + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * per, hi = std::min<int64_t>(batch, lo + per);
    if (lo >= hi) break;
    ts.emplace_back(run, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// Fill out[n_rows, seq_len+1] from caller-provided window starts (the
// sharded-dataset path: the global window -> (shard, local start) mapping
// lives in Python — training/data.py::ShardedTokenBinDataset — and each
// shard's rows arrive here as explicit local offsets).
void orion_loader_gather(void* handle, const int64_t* starts, int64_t n_rows,
                         int32_t* out, int n_threads) {
  auto* L = static_cast<Loader*>(handle);
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_rows) n_threads = static_cast<int>(n_rows);
  auto run = [&](int64_t lo, int64_t hi) {
    if (L->itemsize == 2) {
      gather_explicit<uint16_t>(L, starts, lo, hi, out);
    } else {
      gather_explicit<uint32_t>(L, starts, lo, hi, out);
    }
  };
  if (n_threads <= 1) {
    run(0, n_rows);
    return;
  }
  std::vector<std::thread> ts;
  int64_t per = (n_rows + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * per, hi = std::min<int64_t>(n_rows, lo + per);
    if (lo >= hi) break;
    ts.emplace_back(run, lo, hi);
  }
  for (auto& t : ts) t.join();
}

void orion_loader_close(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  munmap(const_cast<uint8_t*>(L->data), L->file_bytes);
  ::close(L->fd);
  delete L;
}

}  // extern "C"
