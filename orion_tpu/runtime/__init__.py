"""Native runtime bindings (SURVEY.md N2): ctypes over liborion_runtime.so,
with the pure-Python implementations as drop-in fallback.

The .so is optional by design — every API here has a Python twin with the
identical determinism contract (same splitmix64 window stream, same
byte-level vocab), so the framework runs anywhere and the native path is a
pure speedup. ``native_available()`` reports which path is live;
``build()`` compiles the .so in-tree with g++.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_DIR, "liborion_runtime.so")

_lib: Optional[ctypes.CDLL] = None


def build(quiet: bool = True) -> bool:
    """Compile liborion_runtime.so. Returns success."""
    try:
        subprocess.run(
            ["sh", os.path.join(_DIR, "build.sh")],
            check=True,
            capture_output=quiet,
        )
        return True
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO_PATH) and os.environ.get("ORION_TPU_BUILD_RUNTIME"):
        build()
    if not os.path.exists(_SO_PATH):
        return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        return None
    lib.orion_loader_open.restype = ctypes.c_void_p
    lib.orion_loader_open.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_int]
    lib.orion_loader_n_tokens.restype = ctypes.c_int64
    lib.orion_loader_n_tokens.argtypes = [ctypes.c_void_p]
    lib.orion_loader_batch.restype = None
    lib.orion_loader_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int,
    ]
    lib.orion_loader_close.restype = None
    lib.orion_loader_close.argtypes = [ctypes.c_void_p]
    try:  # explicit-starts gather (absent in .so builds predating r5)
        lib.orion_loader_gather.restype = None
        lib.orion_loader_gather.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int,
        ]
    except AttributeError:
        pass
    lib.orion_byte_encode.restype = ctypes.c_int64
    lib.orion_byte_encode.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.orion_byte_encode_file.restype = ctypes.c_int64
    lib.orion_byte_encode_file.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    try:  # corpusgen entry points (absent in .so builds predating r5)
        lib.orion_corpusgen_fit.restype = ctypes.c_void_p
        lib.orion_corpusgen_fit.argtypes = [
            ctypes.POINTER(ctypes.c_uint16),
            ctypes.c_int64,
        ]
        lib.orion_corpusgen_sample.restype = None
        lib.orion_corpusgen_sample.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint16),
        ]
        lib.orion_corpusgen_destroy.restype = None
        lib.orion_corpusgen_destroy.argtypes = [ctypes.c_void_p]
    except AttributeError:
        pass
    try:  # BPE entry points (absent in .so builds predating bpe.cc)
        lib.orion_bpe_create.restype = ctypes.c_void_p
        lib.orion_bpe_create.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
        ]
        lib.orion_bpe_destroy.restype = None
        lib.orion_bpe_destroy.argtypes = [ctypes.c_void_p]
        lib.orion_bpe_encode.restype = ctypes.c_int64
        lib.orion_bpe_encode.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
        ]
    except AttributeError:
        pass
    _lib = lib
    return lib


def native_available() -> bool:
    return _load() is not None


class NativeTokenBinDataset:
    """C++ mmap+gather loader; same (seed, step) -> batch contract as the
    Python TokenBinDataset (training/data.py). Raises ImportError when the
    .so is missing — callers use ``make_fastest_dataset`` to auto-fallback."""

    def __init__(self, path: str, seq_len: int, n_threads: int = 4):
        lib = _load()
        if lib is None:
            raise ImportError("liborion_runtime.so not built (run runtime.build())")
        meta_path = path + ".meta.json"
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            dtype = np.dtype(meta["dtype"])
            self.vocab_size = int(meta.get("vocab_size", np.iinfo(dtype).max + 1))
        else:
            dtype = np.dtype(np.uint16)
            self.vocab_size = 65536
        self._lib = lib
        self._h = lib.orion_loader_open(
            path.encode(), seq_len, int(dtype.itemsize)
        )
        if not self._h:
            raise OSError(f"orion_loader_open failed for {path}")
        self.seq_len = seq_len
        self.n_threads = n_threads
        self.n_tokens = lib.orion_loader_n_tokens(self._h)
        self.n_windows = self.n_tokens - seq_len - 1

    def batch(self, seed: int, step: int, batch_size: int) -> np.ndarray:
        out = np.empty((batch_size, self.seq_len + 1), dtype=np.int32)
        self._lib.orion_loader_batch(
            self._h,
            ctypes.c_uint64(seed),
            ctypes.c_uint64(step),
            batch_size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self.n_threads,
        )
        return out

    def gather(self, starts: np.ndarray) -> np.ndarray:
        """[len(starts), seq_len+1] int32 windows at explicit offsets (the
        sharded-dataset building block; requires an r5+ .so)."""
        if not hasattr(self._lib, "orion_loader_gather"):
            raise ImportError("liborion_runtime.so predates orion_loader_gather")
        starts = np.ascontiguousarray(starts, dtype=np.int64)
        out = np.empty((starts.size, self.seq_len + 1), dtype=np.int32)
        self._lib.orion_loader_gather(
            self._h,
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            starts.size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self.n_threads,
        )
        return out

    def close(self):
        if self._h:
            self._lib.orion_loader_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def make_fastest_dataset(path: str, seq_len: int):
    """Native loader if the .so is present, Python mmap fallback otherwise."""
    if native_available():
        return NativeTokenBinDataset(path, seq_len)
    from orion_tpu.training.data import TokenBinDataset

    return TokenBinDataset(path, seq_len)


class NativeBPE:
    """C++ BPE encoder (runtime/bpe.cc); token-for-token identical to the
    Python ``utils/bpe.py`` encode path (contract-tested). Create from the
    tokenizer's merge list; encode() takes/returns what the Python does."""

    def __init__(self, merges):
        lib = _load()
        if lib is None or not hasattr(lib, "orion_bpe_create"):
            raise ImportError("liborion_runtime.so missing BPE entry points")
        flat = np.asarray(merges, dtype=np.int32).reshape(-1)
        self._lib = lib
        self._h = lib.orion_bpe_create(
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(merges)
        )
        if not self._h:
            raise OSError("orion_bpe_create failed")

    def encode(self, text: str):
        data = text.encode("utf-8")
        if not data:
            return []
        out = np.empty(len(data), dtype=np.int32)
        n = self._lib.orion_bpe_encode(
            self._h,
            data,
            len(data),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return out[:n].tolist()

    def __del__(self):
        try:
            if self._h:
                self._lib.orion_bpe_destroy(self._h)
                self._h = None
        except Exception:
            pass


class NativeCorpusGen:
    """C++ interpolated-trigram corpus sampler (runtime/corpusgen.cc);
    bit-identical to training/corpusgen.py::MarkovModel (contract-tested)
    at ~10M tokens/s — what makes the 100M+-token synthetic pretraining
    corpus (VERDICT r4 #2) a minutes-scale operation."""

    def __init__(self, corpus: np.ndarray):
        lib = _load()
        if lib is None or not hasattr(lib, "orion_corpusgen_fit"):
            raise ImportError("liborion_runtime.so missing corpusgen entries")
        # keep our own copy: the model holds a pointer into this buffer
        self._corpus = np.ascontiguousarray(corpus, dtype=np.uint16)
        self._lib = lib
        self._h = lib.orion_corpusgen_fit(
            self._corpus.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            self._corpus.size,
        )
        if not self._h:
            raise OSError("orion_corpusgen_fit failed (need >= 3 tokens)")

    def sample(self, seed: int, n_out: int, p_uni: float = 0.02,
               p_bi: float = 0.15) -> np.ndarray:
        out = np.empty(n_out, dtype=np.uint16)
        self._lib.orion_corpusgen_sample(
            self._h, ctypes.c_uint64(seed), p_uni, p_bi, n_out,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        )
        return out

    def close(self):
        if self._h:
            self._lib.orion_corpusgen_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def byte_encode_file(in_path: str, out_path: str) -> int:
    """Stream a raw file into a uint16 token-bin (+ sidecar). Native if
    available, Python otherwise. Returns token count."""
    lib = _load()
    if lib is not None:
        n = lib.orion_byte_encode_file(in_path.encode(), out_path.encode())
        if n < 0:
            raise OSError(f"orion_byte_encode_file failed: {in_path}")
    else:
        with open(in_path, "rb") as f:
            data = f.read()
        np.frombuffer(data, dtype=np.uint8).astype(np.uint16).tofile(out_path)
        n = len(data)
    with open(out_path + ".meta.json", "w") as f:
        json.dump({"dtype": "uint16", "count": int(n), "vocab_size": 256}, f)
    return int(n)


__all__ = [
    "build",
    "native_available",
    "NativeTokenBinDataset",
    "NativeBPE",
    "NativeCorpusGen",
    "make_fastest_dataset",
    "byte_encode_file",
]
