#!/bin/sh
# Build the native runtime: liborion_runtime.so (loader + tokenizer).
# Plain C ABI — loaded via ctypes (orion_tpu/runtime/__init__.py).
set -e
cd "$(dirname "$0")"
g++ -O3 -march=native -fPIC -shared -std=c++17 -pthread \
    loader.cc tokenizer.cc bpe.cc corpusgen.cc \
    -o liborion_runtime.so
echo "built $(pwd)/liborion_runtime.so"
