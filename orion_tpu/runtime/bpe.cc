// Native byte-level BPE encoder (SURVEY.md N4). The encode hot path of
// utils/bpe.py::BPETokenizer, bit-identical by contract (tests/test_bpe.py
// compares outputs token-for-token): same pretokenizer semantics as the
// Python regex  \s?[A-Za-z]+ | \s?[0-9]+ | \s?[^\sA-Za-z0-9]+ | \s+
// (ASCII classes; multibyte UTF-8 lands in the "other" class), same greedy
// lowest-rank merge loop, same word cache. Python trains and serializes the
// merges (training is offline, once); this file only encodes — the part
// that runs over every corpus byte.
//
// Plain C ABI, loaded via ctypes (runtime/__init__.py). No dependencies.

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

inline bool is_ws(uint8_t c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
         c == '\v';
}
inline bool is_letter(uint8_t c) {
  return (c >= 'A' && c <= 'Z') || (c >= 'a' && c <= 'z');
}
inline bool is_digit(uint8_t c) { return c >= '0' && c <= '9'; }
// "other": not whitespace, not ASCII alphanumeric (multibyte UTF-8 included)
inline bool is_other(uint8_t c) {
  return !is_ws(c) && !is_letter(c) && !is_digit(c);
}

struct BPE {
  // (a << 32 | b) -> merged id (rank order == id order, ids from 256)
  std::unordered_map<uint64_t, int32_t> ranks;  // immutable after create
  std::unordered_map<std::string, std::vector<int32_t>> cache;
  std::mutex cache_mu;  // ctypes drops the GIL during encode — concurrent
                        // encode() on one tokenizer must not race the cache

  void merge_word(const uint8_t* w, size_t n, std::vector<int32_t>& out) {
    std::string key(reinterpret_cast<const char*>(w), n);
    {
      std::lock_guard<std::mutex> lk(cache_mu);
      auto it = cache.find(key);
      if (it != cache.end()) {
        out.insert(out.end(), it->second.begin(), it->second.end());
        return;
      }
    }
    std::vector<int32_t> parts(n);
    for (size_t i = 0; i < n; i++) parts[i] = w[i];
    while (parts.size() > 1) {
      int32_t best_rank = INT32_MAX;
      size_t best_i = SIZE_MAX;
      for (size_t i = 0; i + 1 < parts.size(); i++) {
        uint64_t k = (uint64_t(uint32_t(parts[i])) << 32) |
                     uint32_t(parts[i + 1]);
        auto r = ranks.find(k);
        if (r != ranks.end() && r->second < best_rank) {
          best_rank = r->second;
          best_i = i;
        }
      }
      if (best_i == SIZE_MAX) break;
      parts[best_i] = best_rank;  // rank IS the merged token id
      parts.erase(parts.begin() + best_i + 1);
    }
    {
      std::lock_guard<std::mutex> lk(cache_mu);
      if (cache.size() < (1u << 20)) cache.emplace(std::move(key), parts);
    }
    out.insert(out.end(), parts.begin(), parts.end());
  }
};

}  // namespace

extern "C" {

void* orion_bpe_create(const int32_t* merges, int64_t n_merges) {
  BPE* h = new BPE();
  h->ranks.reserve(size_t(n_merges) * 2);
  for (int64_t i = 0; i < n_merges; i++) {
    uint64_t k = (uint64_t(uint32_t(merges[2 * i])) << 32) |
                 uint32_t(merges[2 * i + 1]);
    h->ranks.emplace(k, int32_t(256 + i));
  }
  return h;
}

void orion_bpe_destroy(void* handle) { delete static_cast<BPE*>(handle); }

// Encode UTF-8 bytes -> token ids. out must hold >= len entries (merges
// only ever shrink the byte-level tokenization). Returns the token count.
int64_t orion_bpe_encode(void* handle, const uint8_t* s, int64_t len,
                         int32_t* out) {
  BPE* h = static_cast<BPE*>(handle);
  std::vector<int32_t> toks;
  toks.reserve(size_t(len) / 3 + 8);
  int64_t i = 0;
  while (i < len) {
    int64_t start = i;
    uint8_t c = s[i];
    if (is_ws(c)) {
      // \s?X+ alternatives fire only when the ws is followed by that class;
      // otherwise the whole whitespace run is one \s+ token
      if (i + 1 < len && is_letter(s[i + 1])) {
        i += 2;
        while (i < len && is_letter(s[i])) i++;
      } else if (i + 1 < len && is_digit(s[i + 1])) {
        i += 2;
        while (i < len && is_digit(s[i])) i++;
      } else if (i + 1 < len && is_other(s[i + 1])) {
        i += 2;
        while (i < len && is_other(s[i])) i++;
      } else {
        while (i < len && is_ws(s[i])) i++;
      }
    } else if (is_letter(c)) {
      while (i < len && is_letter(s[i])) i++;
    } else if (is_digit(c)) {
      while (i < len && is_digit(s[i])) i++;
    } else {
      while (i < len && is_other(s[i])) i++;
    }
    h->merge_word(s + start, size_t(i - start), toks);
  }
  std::memcpy(out, toks.data(), toks.size() * sizeof(int32_t));
  return int64_t(toks.size());
}

}  // extern "C"
