// Native byte-level tokenizer hot path (SURVEY.md N3).
//
// The reference ships its tokenizer inside the native extension layer
// (BASELINE.json; reference checkout never mounted — SURVEY.md §0). The
// byte-level scheme (ids 0..255 = raw bytes) makes encode a typed copy;
// the native win is doing it without the GIL for large corpora, plus a
// bulk file->token-bin converter that streams without Python overhead.

#include <cstdint>
#include <cstdio>
#include <vector>

extern "C" {

// text[len] -> out[len] int32 ids. Returns count written.
int64_t orion_byte_encode(const uint8_t* text, int64_t len, int32_t* out) {
  for (int64_t i = 0; i < len; ++i) out[i] = static_cast<int32_t>(text[i]);
  return len;
}

// ids[len] -> out[len] bytes; ids outside [0, 255] are skipped.
// Returns count written.
int64_t orion_byte_decode(const int32_t* ids, int64_t len, uint8_t* out) {
  int64_t w = 0;
  for (int64_t i = 0; i < len; ++i) {
    if (ids[i] >= 0 && ids[i] < 256) out[w++] = static_cast<uint8_t>(ids[i]);
  }
  return w;
}

// Stream a raw text/bytes file into a uint16 token-bin file.
// Returns token count, or -1 on IO failure.
int64_t orion_byte_encode_file(const char* in_path, const char* out_path) {
  FILE* in = fopen(in_path, "rb");
  if (!in) return -1;
  FILE* out = fopen(out_path, "wb");
  if (!out) {
    fclose(in);
    return -1;
  }
  std::vector<uint8_t> buf(1 << 20);
  std::vector<uint16_t> tok(1 << 20);
  int64_t total = 0;
  size_t n;
  while ((n = fread(buf.data(), 1, buf.size(), in)) > 0) {
    for (size_t i = 0; i < n; ++i) tok[i] = buf[i];
    if (fwrite(tok.data(), sizeof(uint16_t), n, out) != n) {
      total = -1;
      break;
    }
    total += static_cast<int64_t>(n);
  }
  fclose(in);
  fclose(out);
  return total;
}

}  // extern "C"
