"""`python -m orion_tpu.aot` — ahead-of-time lowering + memory planning for
a sharded train step (SURVEY.md M4 buildability / VERDICT r1 item 8).

Answers "does this config build, shard, and fit?" without touching real
weights or real hardware: the full GSPMD train step is lowered and compiled
against *abstract* state (jax.ShapeDtypeStructs carrying NamedShardings),
so a 7B step can be validated on a laptop-sized host with a virtual
8-device mesh (``--force-cpu-devices N``). Reports:

- per-device parameter / optimizer-state bytes (from the sharding rules)
- the compiler's own memory analysis (argument/output/temp/code bytes)
  when the backend exposes it
- the collectives GSPMD inserted (all-gather / reduce-scatter / all-reduce
  counts in the optimized HLO) — evidence the sharding rules actually
  engaged rather than silently replicating

The reference validates its big configs by launching them (BASELINE.json
config #5 "7B hybrid"; reference checkout never mounted — SURVEY.md §0);
XLA's AOT path lets us make the same claim statically.
"""

from __future__ import annotations

import argparse
import collections
import dataclasses
import json
import re
import sys
from typing import Any, Dict, Optional


def _bytes_per_device(abstract: Any, shardings: Any) -> int:
    """Sum of leaf bytes / shard-factor over the state tree."""
    import jax
    import numpy as np

    total = 0
    for leaf, shd in zip(jax.tree.leaves(abstract), jax.tree.leaves(shardings)):
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        factor = 1
        for dim, ax in enumerate(shd.spec):
            if ax is None or dim >= len(leaf.shape):
                continue
            axes = ax if isinstance(ax, tuple) else (ax,)
            for a in axes:
                factor *= shd.mesh.shape[a]
        total += n * leaf.dtype.itemsize // max(factor, 1)
    return total


def _collective_counts(hlo_text: str) -> Dict[str, int]:
    ops = ("all-gather", "reduce-scatter", "all-reduce", "all-to-all",
           "collective-permute")
    counts: Dict[str, int] = collections.Counter()
    for op in ops:
        counts[op] = len(re.findall(rf"\b{op}(?:-start)?\(", hlo_text))
    # Mosaic kernels land as custom-calls with target "tpu_custom_call":
    # >0 is the proof a backend="pallas" plan actually carries the kernels
    # (vs silently falling back to the XLA forms). Counting bare
    # `custom-call(` would also count AllocateBuffer / async-collective
    # plumbing and overstate kernel presence.
    counts["mosaic_kernels"] = len(
        re.findall(r'custom_call_target="tpu_custom_call"', hlo_text)
    )
    return dict(counts)


def topology_mesh(topology: str, mesh_cfg) -> Any:
    """Mesh over a named TPU topology's ABSTRACT devices (e.g. "v5e:2x4") —
    no hardware attached: jax's topology AOT path hands the real TPU
    compiler (Mosaic included) the target platform, so a plan validated
    here is the exact executable a pod of that shape would run. This is
    strictly stronger evidence than the virtual-CPU mesh: CPU numbers come
    from the CPU backend's memory model and skip Mosaic entirely."""
    from jax.experimental import topologies

    from orion_tpu.parallel.mesh import make_mesh

    topo = topologies.get_topology_desc(platform="tpu", topology_name=topology)
    return make_mesh(mesh_cfg.resolve(len(topo.devices)), devices=topo.devices)


def plan(
    cfg,
    compile_step: bool = True,
    hlo: bool = False,
    mesh: Any = None,
) -> Dict[str, Any]:
    """Lower (and optionally compile) the sharded train step for
    ``cfg: TrainConfig``; return the planning report dict. ``mesh``
    overrides the config-derived device mesh (the --topology path)."""
    import jax
    import numpy as np

    from orion_tpu.training.trainer import Trainer

    trainer = Trainer(cfg, mesh=mesh, materialize=False)
    abstract = trainer.abstract_state()
    batch = jax.ShapeDtypeStruct(
        (cfg.batch_size, cfg.seq_len + 1), np.int32, sharding=trainer.batch_shd
    )

    n_params = sum(
        int(np.prod(l.shape))
        for l in jax.tree.leaves(trainer._abstract.params)
    )
    report: Dict[str, Any] = {
        "config": cfg.model.name,
        "mesh": dict(trainer.mesh.shape),
        "batch_size": cfg.batch_size,
        "seq_len": cfg.seq_len,
        "n_params": n_params,
        "param_bytes_per_device": _bytes_per_device(
            trainer._abstract.params,
            trainer.state_shardings.params,
        ),
        "state_bytes_per_device": _bytes_per_device(
            trainer._abstract, trainer.state_shardings
        ),
    }

    lowered = trainer._step_fn.lower(abstract, batch)
    report["lowered"] = True
    if not compile_step:
        return report

    compiled = lowered.compile()
    report["compiled"] = True
    # these introspection APIs are backend-dependent; record failures rather
    # than silently dropping the sections the tool exists to report
    try:
        hlo_text = compiled.as_text()
        report["collectives"] = _collective_counts(hlo_text)
        if hlo:
            report["hlo_text"] = hlo_text
    except Exception as e:
        report["collectives_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            for k in (
                "argument_size_in_bytes",
                "output_size_in_bytes",
                "temp_size_in_bytes",
                "generated_code_size_in_bytes",
            ):
                v = getattr(ma, k, None)
                if v is not None:
                    report[k] = int(v)
    except Exception as e:
        report["memory_analysis_error"] = f"{type(e).__name__}: {e}"[:200]
    return report


def _decode_abstracts(model_cfg, slots: int, qmode: str, tp: int):
    """Abstract (model, params, carry, rngs, active, shaped) for lowering
    the serving decode programs — shared by :func:`decode_plan` and
    :func:`decode_cost_entries` so the two can never key off different
    shapes. With ``tp > 1`` everything carries the serving mesh's
    NamedShardings (params by the training rules, state head-sharded,
    per-slot vectors replicated)."""
    import jax
    import jax.numpy as jnp

    from orion_tpu.models.transformer import TransformerLM, init_decode_state

    tp = max(int(tp), 1)
    model = TransformerLM(model_cfg, quant=qmode if qmode != "off" else "")
    mesh = None
    if tp > 1:
        from orion_tpu.parallel.decode import serving_mesh

        mesh = serving_mesh(tp)

    prompt = jax.ShapeDtypeStruct((1, 8), jnp.int32)
    abstract = jax.eval_shape(model.init, jax.random.PRNGKey(0), prompt)
    states = jax.eval_shape(lambda: init_decode_state(model_cfg, slots))
    if mesh is not None:
        from orion_tpu.parallel.decode import (
            decode_param_shardings,
            decode_state_shardings,
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        sds = lambda l, s: jax.ShapeDtypeStruct(  # noqa: E731
            l.shape, l.dtype, sharding=s
        )
        params = jax.tree.map(
            sds, abstract, decode_param_shardings(abstract, mesh)
        )
        states = jax.tree.map(
            sds, states, decode_state_shardings(states, mesh)
        )
        rep = NamedSharding(mesh, P())
        shaped = lambda shape, dt: jax.ShapeDtypeStruct(  # noqa: E731
            shape, dt, sharding=rep
        )
    else:
        params = abstract
        shaped = jax.ShapeDtypeStruct
    vec = lambda dt: shaped((slots,), dt)  # noqa: E731
    carry = (
        vec(jnp.int32), states, vec(jnp.int32), vec(jnp.int32),
        vec(jnp.bool_),
    )
    rngs = shaped((slots, 2), jnp.uint32)
    active = vec(jnp.bool_)
    return model, params, carry, rngs, active, shaped


def _lowered_cost(lowered) -> Dict[str, Any]:
    """Flops/bytes from a Lowered's HLO cost analysis, normalized to one
    flat dict (some jax versions return a per-device list)."""
    ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}
    out: Dict[str, Any] = {}
    for src, dst in (("flops", "flops"), ("bytes accessed", "bytes_accessed"),
                     ("transcendentals", "transcendentals")):
        v = ca.get(src)
        if v is not None:
            out[dst] = float(v)
    return out


# identity -> harvested entries; the harvest is pure (abstract shapes in,
# cost numbers out), so one process-wide memo makes repeated Server
# constructions of the same engine shape free after the first
_COST_MEMO: Dict[tuple, list] = {}


def decode_cost_entries(
    model_cfg,
    slots: int = 8,
    chunk: int = 16,
    bucket: int = 0,
    prefill_chunk: int = 0,
    qmode: str = "off",
    tp: int = 0,
    spec_depth: int = 0,
) -> list:
    """The cost-ledger harvest (ISSUE 15): LOWER (never compile — the
    jit caches stay untouched, which the zero-compile acceptance pins)
    each decode program this engine shape actually runs and extract XLA
    ``cost_analysis()`` flops/bytes. Returns entries
    ``{"kind", "key", "flops", "bytes_accessed", ...}`` keyed by the
    golden-snapshot identity. ``bucket`` is the staged-buffer width the
    unified program is costed at (the engine's largest prefill bucket —
    the worst-case piece); a per-program failure is recorded on its
    entry, never raised: serving must come up even when the harvest
    can't."""
    import time as _time

    tp = max(int(tp), 1)
    memo_key = (repr(model_cfg), slots, chunk, int(bucket),
                int(prefill_chunk), qmode, tp, int(spec_depth))
    got = _COST_MEMO.get(memo_key)
    if got is not None:
        return [dict(e) for e in got]

    import jax.numpy as jnp

    from orion_tpu.generate import (
        SampleConfig,
        _decode_batched_chunk_jit,
        _decode_batched_prefill_chunk_jit,
        _decode_batched_spec_round_jit,
    )
    from orion_tpu.obs.cost import program_key

    model, params, carry, rngs, active, shaped = _decode_abstracts(
        model_cfg, slots, qmode, tp
    )
    vec = lambda dt: shaped((slots,), dt)  # noqa: E731
    sample = SampleConfig()
    base = {"slots": slots, "chunk": chunk, "qmode": qmode, "tp": tp}
    entries = []

    def harvest(kind: str, key: Dict[str, Any], lower) -> None:
        entry: Dict[str, Any] = {
            "kind": kind, "key": program_key(kind, **key),
        }
        t0 = _time.monotonic()
        try:
            entry.update(_lowered_cost(lower()))
            entry["lower_ms"] = round((_time.monotonic() - t0) * 1e3, 3)
        except Exception as e:  # surface on the entry, never crash serving
            entry["error"] = f"{type(e).__name__}: {e}"[:200]
        entries.append(entry)

    harvest("decode_batched", dict(base), lambda: (
        _decode_batched_chunk_jit.lower(
            model, params, carry, rngs, active, int(chunk), sample
        )
    ))
    pchunk = 0
    if int(prefill_chunk) > 0 and int(bucket) > 0:
        from orion_tpu.ops.dispatch import resolve, resolve_chunk

        align = resolve_chunk(
            model_cfg.chunk, model_cfg.max_seq_len, resolve(model_cfg.backend)
        )
        pchunk = -(-int(prefill_chunk) // align) * align
        pbuf = shaped((slots, int(bucket)), jnp.int32)
        harvest(
            "unified_prefill",
            dict(base, bucket=int(bucket), prefill_chunk=pchunk),
            lambda: _decode_batched_prefill_chunk_jit.lower(
                model, params, carry, rngs, active, pbuf,
                vec(jnp.int32), vec(jnp.int32), int(chunk),
                min(pchunk, int(bucket)), sample,
            ),
        )
    if int(spec_depth) > 0:
        harvest(
            "spec_round",
            {"slots": slots, "spec_depth": int(spec_depth),
             "qmode": qmode, "tp": tp},
            lambda: _decode_batched_spec_round_jit.lower(
                model, params, carry, rngs, active, vec(jnp.bool_),
                int(spec_depth), sample,
            ),
        )
    _COST_MEMO[memo_key] = [dict(e) for e in entries]
    return entries


def decode_plan(
    model_cfg,
    slots: int = 8,
    chunk: int = 16,
    prefill_buckets=(),
    prefill_chunk: int = 0,
    qmode: str = "off",
    tp: int = 0,
    spec_depth: int = 0,
    compile_step: bool = True,
    lower: bool = True,
    store=None,
    sample=None,
) -> Dict[str, Any]:
    """The SERVING-side inventory ``plan`` never had (ISSUE 14): every
    decode/prefill executable a replica of this shape compiles, keyed
    exactly like the jit caches — (slots, chunk, bucket, qmode, tp) —
    lowered (and optionally compiled) against abstract sharded state.
    This is the complete program list ROADMAP item 4's warm-start work
    needs to persist: a respawned replica serving these shapes runs
    precisely these executables, nothing else (the engine's
    one-compile-per-key contract is cache-stat-asserted in tests).

    Per program: the GSPMD collectives (for tp plans: the two
    per-block all-reduces per decode step — evidence the mesh engaged)
    and the compiler's code size, the artifact a warm-start cache would
    key and store. ``lower=False`` skips lowering entirely and returns
    the pure inventory (identity keys only) — the cheap side Tier E's
    plan-drift rule and :func:`verify_decode_plan` diff against the
    declared universe.

    ``store`` (a :class:`~orion_tpu.serving.exec_store.ExecStore`)
    engages the warm-start path both ways: a program whose identity is
    already COMMITTED in the store short-circuits (``warm: True`` on its
    entry, no lowering — repeated ``--verify`` preflights cost one
    listdir per program), and a freshly compiled program is serialized
    and PUBLISHED (``published_gen`` on the entry; per-entry
    ``publish_error`` on failure, never raised — the plan must come out
    even when the store is down).

    ``sample`` is the SampleConfig the programs are specialized on (a
    jit static, part of every executable's content address — the CLIs
    default temperature 0.8, NOT the dataclass default 1.0, so a warm
    meant for CLI-launched replicas must be published under the same
    sampling statics). None = dataclass defaults."""
    tp = max(int(tp), 1)
    base_key = {"slots": slots, "chunk": chunk, "qmode": qmode, "tp": tp}

    # pass 1: the pure inventory — entry identities plus deferred
    # lowering thunks, NO jax work yet (thunks only run in pass 2)
    programs: list = []
    jobs: list = []

    def add(kind: str, key: Dict[str, Any], lower_thunk) -> None:
        entry: Dict[str, Any] = {"kind": kind, **key}
        programs.append(entry)
        jobs.append((entry, lower_thunk))

    add("decode_batched", dict(base_key), lambda env: (
        env["decode_batched"].lower(
            env["model"], env["params"], env["carry"], env["rngs"],
            env["active"], int(chunk), env["sample"],
        )
    ))
    # the engine's in-scan piece boundaries align to the linear-attention
    # chunk (SlotEngine rounds the knob up; batching.py chunk_align) — the
    # inventory must list the pchunk the replica actually compiles, and
    # prefill_chunk=0 means host-side prefill: no unified program exists
    pchunk = 0
    if int(prefill_chunk) > 0:
        from orion_tpu.ops.dispatch import resolve, resolve_chunk

        align = resolve_chunk(
            model_cfg.chunk, model_cfg.max_seq_len, resolve(model_cfg.backend)
        )
        pchunk = -(-int(prefill_chunk) // align) * align
    for bucket in prefill_buckets or ():
        if pchunk:
            add(
                "unified_prefill",
                dict(base_key, bucket=int(bucket), prefill_chunk=pchunk),
                lambda env, bucket=bucket, pchunk=pchunk: (
                    env["unified_prefill"].lower(
                        env["model"], env["params"], env["carry"],
                        env["rngs"], env["active"],
                        env["shaped"]((slots, int(bucket)), env["i32"]),
                        env["vec"](env["i32"]), env["vec"](env["i32"]),
                        int(chunk), pchunk, env["sample"],
                    )
                ),
            )
        # the host-side bucketed prefill (admission with prefill_chunk=0,
        # the ladder's re-prefill rung, prefix publishes): batch 1
        add(
            "prefill_bucketed",
            {"bucket": int(bucket), "qmode": qmode, "tp": tp},
            lambda env, bucket=bucket: env["prefill_bucketed"].lower(
                env["model"], env["params"],
                env["shaped"]((1, int(bucket)), env["i32"]), env["sample"],
                env["shaped"]((2,), env["u32"]),
                env["shaped"]((), env["i32"]),
                env["shaped"]((1,), env["bool"]),
                env["shaped"]((), env["i32"]),
            ),
        )
    if spec_depth:
        add(
            "spec_round",
            {"slots": slots, "spec_depth": int(spec_depth),
             "qmode": qmode, "tp": tp},
            lambda env: env["spec_round"].lower(
                env["model"], env["params"], env["carry"], env["rngs"],
                env["active"], env["vec"](env["bool"]),
                int(spec_depth), env["sample"],
            ),
        )

    # pass 2: lower (and optionally compile) each planned program
    if lower:
        import jax.numpy as jnp

        from orion_tpu.generate import (
            SampleConfig,
            _decode_batched_chunk_jit,
            _decode_batched_prefill_chunk_jit,
            _decode_batched_spec_round_jit,
            _prefill_carry_bucketed_jit,
        )

        model, params, carry, rngs, active, shaped = _decode_abstracts(
            model_cfg, slots, qmode, tp
        )
        env = {
            "model": model, "params": params, "carry": carry,
            "rngs": rngs, "active": active, "shaped": shaped,
            "vec": lambda dt: shaped((slots,), dt),
            "sample": sample if sample is not None else SampleConfig(),
            "i32": jnp.int32, "u32": jnp.uint32, "bool": jnp.bool_,
            "decode_batched": _decode_batched_chunk_jit,
            "unified_prefill": _decode_batched_prefill_chunk_jit,
            "prefill_bucketed": _prefill_carry_bucketed_jit,
            "spec_round": _decode_batched_spec_round_jit,
        }
        sample_fp = ""
        if store is not None:
            from orion_tpu.serving.exec_store import sample_fingerprint

            sample_fp = sample_fingerprint(env["sample"])
        for entry, thunk in jobs:
            ident = dict(entry)  # pure identity until this pass mutates it
            if store is not None and store.has(ident, sample_fp):
                # content-hash short-circuit: a COMMITTED executable is
                # the proof this program lowers and compiles — repeated
                # preflights (bench.py runs --verify before real work)
                # cost one listdir per program instead of a lowering
                entry["warm"] = True
                entry["lowered"] = True
                if compile_step:
                    entry["compiled"] = True
                continue
            try:
                lowered = thunk(env)
                entry["lowered"] = True
                try:
                    # the cost-ledger figures (ISSUE 15) ride the
                    # inventory too: the warm-start program list doubles
                    # as the fleet's per-program price sheet
                    entry["cost"] = _lowered_cost(lowered)
                except Exception as e:
                    entry["cost_error"] = f"{type(e).__name__}: {e}"[:120]
                if compile_step:
                    compiled = lowered.compile()
                    entry["compiled"] = True
                    if store is not None:
                        try:
                            entry["published_gen"] = store.publish(
                                ident, compiled, sample_fp
                            )
                        except Exception as e:
                            # the plan must come out even when the store
                            # is down; warm() surfaces these per-entry
                            entry["publish_error"] = (
                                f"{type(e).__name__}: {e}"[:200]
                            )
                    try:
                        entry["collectives"] = _collective_counts(
                            compiled.as_text()
                        )
                    except Exception as e:
                        entry["collectives_error"] = (
                            f"{type(e).__name__}: {e}"[:120]
                        )
                    try:
                        ma = compiled.memory_analysis()
                        if ma is not None:
                            v = getattr(
                                ma, "generated_code_size_in_bytes", None
                            )
                            if v is not None:
                                entry["generated_code_size_in_bytes"] = (
                                    int(v)
                                )
                    except Exception:
                        pass
            except Exception as e:  # surface, never crash the inventory
                entry["error"] = f"{type(e).__name__}: {e}"[:200]
    return {
        "config": model_cfg.name,
        "qmode": qmode,
        "tp": tp,
        "slots": slots,
        "chunk": chunk,
        "prefill_buckets": list(prefill_buckets or ()),
        "prefill_chunk_aligned": pchunk,
        "spec_depth": int(spec_depth),
        "n_programs": len(programs),
        "programs": programs,
    }


def warm(
    model_cfg,
    store,
    slots: int = 8,
    chunk: int = 16,
    prefill_buckets=(),
    prefill_chunk: int = 0,
    qmode: str = "off",
    tp: int = 0,
    spec_depth: int = 0,
    sample=None,
) -> Dict[str, Any]:
    """Serialize the whole :func:`decode_plan` universe of one footprint
    into ``store`` (ROADMAP item 1's publish half): compile every
    program a replica of this shape runs and publish each executable
    under its content address. Idempotent and cheap to re-run — a
    program already committed short-circuits on the content hash
    without lowering. Returns the plan report with warm-path summary
    fields (``warmed`` fresh publishes, ``already_warm``
    short-circuits, ``publish_errors``). ``sample`` must be the
    SampleConfig replicas will serve with (see :func:`decode_plan`) —
    a warm under the wrong sampling statics publishes executables no
    lookup ever addresses."""
    report = decode_plan(
        model_cfg, slots=slots, chunk=chunk,
        prefill_buckets=prefill_buckets, prefill_chunk=prefill_chunk,
        qmode=qmode, tp=tp, spec_depth=spec_depth,
        compile_step=True, store=store, sample=sample,
    )
    progs = report.get("programs", ())
    report["warmed"] = sum(
        1 for p in progs if p.get("published_gen") is not None
    )
    report["already_warm"] = sum(1 for p in progs if p.get("warm"))
    report["publish_errors"] = [
        p["publish_error"] for p in progs if p.get("publish_error")
    ]
    return report


def verify_decode_plan(report: Dict[str, Any]) -> list:
    """Diff a :func:`decode_plan` report against the DECLARED universe
    (``analysis/programs.py`` — ``expected_decode_universe`` reproduces
    the plan from each decode row's ``plan`` applicability). Returns
    human-readable mismatch strings, empty when plan == declarations —
    the ``--decode --verify`` gate Tier E's plan-drift rule mirrors."""
    from orion_tpu.analysis import programs as _decls
    from orion_tpu.analysis.program_audit import _ident

    expected = _decls.expected_decode_universe(
        slots=report["slots"], chunk=report["chunk"],
        prefill_buckets=tuple(report.get("prefill_buckets", ())),
        prefill_chunk=report.get("prefill_chunk_aligned", 0),
        qmode=report["qmode"], tp=report["tp"],
        spec_depth=report.get("spec_depth", 0),
    )
    inv = {_ident(p) for p in report.get("programs", ())}
    exp = {_ident(e) for e in expected}
    msgs = [
        f"declared program missing from plan: {dict(k)!r}"
        for k in sorted(exp - inv)
    ] + [
        f"planned program not in declared universe: {dict(k)!r}"
        for k in sorted(inv - exp)
    ]
    msgs += [
        f"planned program fails to lower: {p.get('kind')}: {p['error']}"
        for p in report.get("programs", ()) if p.get("error")
    ]
    return msgs


def main(argv=None) -> int:
    p = argparse.ArgumentParser("orion_tpu.aot")
    p.add_argument("cmd", nargs="?", choices=["warm"], default=None,
                   help="warm: compile the --decode universe and publish "
                        "every executable into --exec-dir (implies "
                        "--decode); default: report only")
    p.add_argument("--config", default="hybrid_7b")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=None,
                   help="default: model max_seq_len")
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--ep", type=int, default=1)
    p.add_argument("--optimizer", default="adamw")
    p.add_argument("--lower-only", action="store_true",
                   help="skip XLA compilation (faster; no memory analysis)")
    p.add_argument("--force-cpu-devices", type=int, default=0,
                   help="plan on N virtual CPU devices instead of real chips")
    p.add_argument("--topology", default="",
                   help="plan against a named TPU topology's real compiler "
                        "without hardware, e.g. v5e:2x4 (overrides "
                        "--force-cpu-devices)")
    p.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                   help="ModelConfig override, e.g. --set backend=pallas")
    # -- serving-side inventory (ISSUE 14): the decode/prefill
    # executables a replica of this shape compiles, per
    # (slots, chunk, bucket, qmode, tp) — the warm-start program list
    p.add_argument("--decode", action="store_true",
                   help="plan the batched decode/prefill executables "
                        "instead of the train step (--slots/--chunk/"
                        "--prefill-chunk/--qmode/--spec-depth; --tp is "
                        "the serving mesh footprint)")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--chunk", type=int, default=16)
    p.add_argument("--prefill-chunk", type=int, default=64)
    p.add_argument("--prefill-buckets", default="pow2",
                   help="bucket spec as in serving (pow2 | a,b,c | off)")
    p.add_argument("--qmode", default="off", choices=["off", "int8", "int4"])
    p.add_argument("--spec-depth", type=int, default=0)
    p.add_argument("--verify", action="store_true",
                   help="with --decode: assert the plan inventory exactly "
                        "matches the declared program universe "
                        "(analysis/programs.py) — exit 1 on drift")
    p.add_argument("--exec-dir", default="",
                   help="AOT executable store root (serving/exec_store.py): "
                        "`warm` publishes into it; --decode/--verify "
                        "short-circuit per-program on a committed entry")
    p.add_argument("--params-id", default="",
                   help="weights identity for the executable store "
                        "(default: '<config>:ov=<overrides-hash>:seed=0', "
                        "exactly what the serving/fleet CLIs derive for "
                        "seeded-init params — pin it to the CLI-printed id "
                        "when serving a real checkpoint)")
    p.add_argument("--temperature", type=float, default=0.8,
                   help="sampling statics the executables are specialized "
                        "on (jit statics, part of the content address) — "
                        "defaults MATCH the serving/fleet CLI defaults, "
                        "not the SampleConfig dataclass defaults")
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=1.0)
    p.add_argument("--eos", type=int, default=-1,
                   help="eos token id baked into the sampling statics "
                        "(-1 = none, the CLI default without --tokenizer "
                        "--eos)")
    args = p.parse_args(argv)
    if args.cmd == "warm":
        if not args.exec_dir:
            p.error("warm requires --exec-dir")
        args.decode = True

    if args.topology:
        # the topology client compiles for the named TPU target; the DEFAULT
        # backend is only ever touched for small concrete arrays (rng keys),
        # and on this kind of box the default TPU plugin may be busy or
        # absent — keep those on cpu so planning never waits on a chip
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif args.force_cpu_devices:
        import jax

        from orion_tpu.utils.devices import ensure_virtual_devices

        jax.config.update("jax_platforms", "cpu")
        ensure_virtual_devices(args.force_cpu_devices)

    from orion_tpu.models.configs import get_config
    from orion_tpu.parallel.mesh import MeshConfig
    from orion_tpu.training.trainer import TrainConfig

    model = get_config(args.config)
    if args.set:
        from orion_tpu.utils.config import apply_overrides, parse_set_overrides

        model = apply_overrides(model, parse_set_overrides(args.set))
    if args.decode:
        from orion_tpu.serving.batching import parse_buckets

        store = None
        if args.exec_dir:
            # identity must match what a CLI-launched Server derives
            # EXACTLY (params_id|qmode) or warm entries can never hit
            # at serving time. Both serving CLIs always pass an explicit
            # '<config>:ov=<fp>:seed=<seed>' (or ':ckpt=...:step=...')
            # params_id — the config-hash params_identity fallback in
            # Server only applies to embedded use, so default to the
            # CLI-shaped seeded-init id here
            from orion_tpu.serving.exec_store import ExecStore
            from orion_tpu.serving.prefix_store import overrides_fingerprint
            from orion_tpu.utils.config import parse_set_overrides as _pso

            ov = overrides_fingerprint(_pso(args.set) if args.set else {})
            pid = args.params_id or f"{args.config}:ov={ov}:seed=0"
            store = ExecStore(
                args.exec_dir, identity=f"{pid}|{args.qmode}"
            )
        from orion_tpu.generate import SampleConfig

        footprint = dict(
            slots=args.slots,
            chunk=args.chunk,
            prefill_buckets=parse_buckets(
                args.prefill_buckets, model.max_seq_len
            ),
            prefill_chunk=args.prefill_chunk,
            qmode=args.qmode,
            tp=args.tp,
            spec_depth=args.spec_depth,
            # sampling statics ride the content address; defaults track
            # the serving/fleet CLI defaults (temperature 0.8), NOT the
            # dataclass defaults, so default warm hits default serve
            sample=SampleConfig(
                args.temperature, args.top_k, args.top_p,
                eos_token=args.eos,
            ),
        )
        if args.cmd == "warm":
            report = warm(model, store, **footprint)
            print(json.dumps(report))
            for msg in report["publish_errors"]:
                print(f"aot warm: publish failed: {msg}", file=sys.stderr)
            return 1 if report["publish_errors"] else 0
        report = decode_plan(
            model, compile_step=not args.lower_only, store=store,
            **footprint,
        )
        if args.verify:
            mismatches = verify_decode_plan(report)
            report["verified"] = not mismatches
            print(json.dumps(report))
            for m in mismatches:
                print(f"decode-plan verify: {m}", file=sys.stderr)
            return 1 if mismatches else 0
        print(json.dumps(report))
        return 0
    seq_len = args.seq_len or model.max_seq_len
    if seq_len > model.max_seq_len:
        model = dataclasses.replace(model, max_seq_len=seq_len)
    cfg = TrainConfig(
        model=model,
        batch_size=args.batch_size,
        seq_len=seq_len,
        optimizer=args.optimizer,
        mesh=MeshConfig(dp=args.dp, fsdp=args.fsdp, tp=args.tp, sp=args.sp,
                        pp=args.pp, ep=args.ep),
    )
    mesh = topology_mesh(args.topology, cfg.mesh) if args.topology else None
    report = plan(cfg, compile_step=not args.lower_only, mesh=mesh)
    if args.topology:
        report["topology"] = args.topology
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
