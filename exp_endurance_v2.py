"""ENDURANCE_v2 orchestrator (VERDICT r4 #2 + missing #1): the r4 endurance
run proved the LOOP (loader wraparound, orbax-under-load, SIGKILL resume,
throughput stability) but cycled a 3.7M-token corpus ~34x — held-out ppl
bottomed at ~2250 steps and ROSE, i.e. the trajectory measured memorization.
This run replaces that regime:

- corpus: data/pretrain/ — 160M tokens in 10 shards sampled from the
  interpolated-trigram source fitted on the real BPE corpus
  (training/corpusgen.py; never repeats, entropy floor set by the
  interpolation weights), streamed through ShardedTokenBinDataset + the
  C++ loader's explicit-starts gather;
- eval: data/pretrain/eval.bin — a held-out 2M-token sample (decorrelated
  seed), evaluated every 250 steps through the STEP-KEYED eval_factory
  (r4's fix, now exercised across a crash-resume end to end);
- trainer: the r5 headline operating point — b12 x T2048, remat_skip=6,
  adafactor, param_storage=bfloat16_sr (R5SWEEP.jsonl: 14,605 tok/s) —
  so the convergence story covers the storage mode the benches ship;
- same deliberate mid-async-save SIGKILL + crash-resume as v1.

Success = monotone-falling held-out ppl across the full run (the r4
failure mode), bitwise-consistent resume, flat tok/s, 0 non-finite steps.
Writes ENDURANCE_V2.json; run on the real chip (hours).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
RUN_DIR = os.path.join(REPO, "runs", "endurance_v2")
METRICS = os.path.join(RUN_DIR, "metrics.jsonl")
LOG = os.path.join(RUN_DIR, "train.log")
STEPS = 6000
KILL_AT = 2620  # checkpoint lands at 2500; kill well into the next stretch

CMD = [
    sys.executable, "-m", "orion_tpu.train",
    "--config", "lm_1b3",
    "--data", os.path.join(REPO, "data", "pretrain"),
    "--eval-data", os.path.join(REPO, "data", "pretrain", "eval.bin"),
    "--eval-every", "250",
    "--steps", str(STEPS),
    "--batch-size", "12",
    "--seq-len", "2048",
    "--lr", "2e-4",
    "--ckpt-dir", os.path.join(RUN_DIR, "ckpt"),
    "--log-path", METRICS,
    "--set", "model.remat_skip=6",
    "--set", "optimizer=adafactor",
    "--set", "param_storage=bfloat16_sr",
    "--set", "warmup_steps=200",
    "--set", "ckpt_every=500",
    "--set", "log_every=20",
]


def read_metrics():
    rows = []
    if os.path.exists(METRICS):
        with open(METRICS) as f:
            for line in f:
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # torn tail line from the SIGKILL
    return rows


def last_step(rows):
    return max((r["step"] for r in rows), default=0)


def launch(log_f):
    # own process group so the SIGKILL takes the prefetch thread's process
    # tree with it, exactly like an OOM-killer or preemption would
    return subprocess.Popen(
        CMD, cwd=REPO, stdout=log_f, stderr=subprocess.STDOUT,
        start_new_session=True,
    )


def main() -> int:
    os.makedirs(RUN_DIR, exist_ok=True)
    t0 = time.time()
    evidence = {"cmd": " ".join(CMD), "steps": STEPS, "kill_at": KILL_AT,
                "corpus_tokens": 160_000_000, "eval_tokens": 2_000_000}

    with open(LOG, "a", buffering=1) as log_f:
        log_f.write(f"\n=== phase 1 launch {time.ctime()} ===\n")
        proc = launch(log_f)
        killed_at = None
        while proc.poll() is None:
            time.sleep(20)
            s = last_step(read_metrics())
            if s >= KILL_AT:
                os.killpg(proc.pid, signal.SIGKILL)
                proc.wait()
                killed_at = s
                break
        if killed_at is None:
            evidence["error"] = f"phase 1 exited rc={proc.returncode} before kill"
            evidence["last_step"] = last_step(read_metrics())
            with open(os.path.join(REPO, "ENDURANCE_V2.json"), "w") as f:
                json.dump(evidence, f, indent=1)
            return 1
        evidence["killed_at_logged_step"] = killed_at
        evidence["phase1_wall_s"] = round(time.time() - t0, 1)
        log_f.write(f"\n=== SIGKILL at logged step {killed_at}; "
                    f"relaunch {time.ctime()} ===\n")

        t1 = time.time()
        proc = launch(log_f)
        rc = proc.wait()
        evidence["phase2_rc"] = rc
        evidence["phase2_wall_s"] = round(time.time() - t1, 1)

    rows = read_metrics()
    train_rows = [r for r in rows if "tokens_per_sec" in r]
    eval_rows = [r for r in rows if "eval_ppl" in r]
    steps_seen = [r["step"] for r in rows]
    resume_overlap = sorted(
        {s for s in steps_seen if steps_seen.count(s) > 1}
    )
    tps = [r["tokens_per_sec"] for r in train_rows]
    q = max(1, len(tps) // 4)
    # the headline claim, machine-checked: held-out ppl must fall across
    # the run — compare each eval point to the best seen before it
    traj = [
        {"step": r["step"], "eval_ppl": round(r["eval_ppl"], 3)}
        for r in eval_rows
    ]
    # dedupe resumed evals (same step twice): keep the LAST occurrence
    dedup = {}
    for r in traj:
        dedup[r["step"]] = r["eval_ppl"]
    ordered = [dedup[s] for s in sorted(dedup)]
    rises = sum(
        1 for i in range(1, len(ordered)) if ordered[i] > min(ordered[:i])
    )
    evidence.update({
        "total_wall_s": round(time.time() - t0, 1),
        "final_step": last_step(rows),
        "log_rows": len(rows),
        "tokens_trained": last_step(rows) * 12 * 2048,
        "loss_first": train_rows[0]["loss"] if train_rows else None,
        "loss_last": train_rows[-1]["loss"] if train_rows else None,
        "eval_ppl_trajectory": traj,
        "eval_ppl_first": ordered[0] if ordered else None,
        "eval_ppl_last": ordered[-1] if ordered else None,
        "eval_points_above_running_min": rises,
        "tok_s_mean_first_quartile": round(sum(tps[:q]) / q, 1) if tps else None,
        "tok_s_mean_last_quartile": round(sum(tps[-q:]) / q, 1) if tps else None,
        "tok_s_min": round(min(tps), 1) if tps else None,
        "tok_s_max": round(max(tps), 1) if tps else None,
        "nonfinite_total": train_rows[-1].get("nonfinite_total") if train_rows else None,
        "resumed_steps_recovered": resume_overlap[:5] + (["..."] if len(resume_overlap) > 5 else []),
        "n_resumed_overlap_rows": len(resume_overlap),
    })
    with open(os.path.join(REPO, "ENDURANCE_V2.json"), "w") as f:
        json.dump(evidence, f, indent=1)
    print(json.dumps(evidence, indent=1))
    return 0 if evidence.get("phase2_rc") == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
