"""Endurance orchestrator (r3 VERDICT #1): one sustained flagship pretraining
run that exercises the WHOLE system at duration — C++ loader wraparound over
the real BPE corpus (~3.7M tokens cycled ~34x), fused CE + remat_skip=6 +
adafactor at the shipped b12 operating point, async orbax saves under load,
periodic held-out eval, the nonfinite counter — plus a DELIBERATE mid-run
SIGKILL followed by a crash-resume, the failure mode checkpointing exists for.

Phases:
  1. launch `python -m orion_tpu.train` (lm_1b3, 5200 steps) as a subprocess
  2. watch metrics.jsonl; once step >= KILL_AT (a step safely past the 2500
     checkpoint), SIGKILL the process group — no warning, no flush
  3. relaunch the identical command; train.py resumes from the latest
     complete checkpoint (orbax ignores the torn async save, data stream is
     a pure function of (seed, step))
  4. write ENDURANCE.json: loss/eval trajectory summary, tok/s stability
     (first vs last quartile), kill/resume evidence, wall clock

Run on the real chip: `python exp_endurance.py` (hours).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
RUN_DIR = os.path.join(REPO, "runs", "endurance")
METRICS = os.path.join(RUN_DIR, "metrics.jsonl")
LOG = os.path.join(RUN_DIR, "train.log")
STEPS = 5200
KILL_AT = 2620  # checkpoint lands at 2500; kill well into the next stretch

CMD = [
    sys.executable, "-m", "orion_tpu.train",
    "--config", "lm_1b3",
    "--data", os.path.join(REPO, "data", "train.bin"),
    "--eval-data", os.path.join(REPO, "data", "val.bin"),
    "--eval-every", "250",
    "--steps", str(STEPS),
    "--batch-size", "12",
    "--seq-len", "2048",
    "--lr", "2e-4",
    "--ckpt-dir", os.path.join(RUN_DIR, "ckpt"),
    "--log-path", METRICS,
    "--set", "model.remat_skip=6",
    "--set", "optimizer=adafactor",
    "--set", "warmup_steps=200",
    "--set", "ckpt_every=500",
    "--set", "log_every=20",
]


def read_metrics():
    rows = []
    if os.path.exists(METRICS):
        with open(METRICS) as f:
            for line in f:
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # torn tail line from the SIGKILL
    return rows


def last_step(rows):
    return max((r["step"] for r in rows), default=0)


def launch(log_f):
    # own process group so the SIGKILL takes the prefetch thread's process
    # tree with it, exactly like an OOM-killer or preemption would
    return subprocess.Popen(
        CMD, cwd=REPO, stdout=log_f, stderr=subprocess.STDOUT,
        start_new_session=True,
    )


def main() -> int:
    os.makedirs(RUN_DIR, exist_ok=True)
    t0 = time.time()
    evidence = {"cmd": " ".join(CMD), "steps": STEPS, "kill_at": KILL_AT}

    with open(LOG, "a", buffering=1) as log_f:
        log_f.write(f"\n=== phase 1 launch {time.ctime()} ===\n")
        proc = launch(log_f)
        killed_at = None
        while proc.poll() is None:
            time.sleep(20)
            s = last_step(read_metrics())
            if s >= KILL_AT:
                os.killpg(proc.pid, signal.SIGKILL)
                proc.wait()
                killed_at = s
                break
        if killed_at is None:
            # finished (or died) before the kill threshold — record and bail
            evidence["error"] = f"phase 1 exited rc={proc.returncode} before kill"
            evidence["last_step"] = last_step(read_metrics())
            with open(os.path.join(REPO, "ENDURANCE.json"), "w") as f:
                json.dump(evidence, f, indent=1)
            return 1
        evidence["killed_at_logged_step"] = killed_at
        evidence["phase1_wall_s"] = round(time.time() - t0, 1)
        log_f.write(f"\n=== SIGKILL at logged step {killed_at}; "
                    f"relaunch {time.ctime()} ===\n")

        t1 = time.time()
        proc = launch(log_f)
        rc = proc.wait()
        evidence["phase2_rc"] = rc
        evidence["phase2_wall_s"] = round(time.time() - t1, 1)

    rows = read_metrics()
    train_rows = [r for r in rows if "tokens_per_sec" in r]
    eval_rows = [r for r in rows if "eval_ppl" in r]
    steps_seen = [r["step"] for r in rows]
    # resume evidence: the log contains steps both sides of the kill point,
    # and the resumed stretch re-covers (ckpt_step, killed_at]
    resume_overlap = sorted(
        {s for s in steps_seen if steps_seen.count(s) > 1}
    )
    tps = [r["tokens_per_sec"] for r in train_rows]
    q = max(1, len(tps) // 4)
    evidence.update({
        "total_wall_s": round(time.time() - t0, 1),
        "final_step": last_step(rows),
        "log_rows": len(rows),
        "tokens_trained": last_step(rows) * 12 * 2048,
        "loss_first": train_rows[0]["loss"] if train_rows else None,
        "loss_last": train_rows[-1]["loss"] if train_rows else None,
        "eval_ppl_trajectory": [
            {"step": r["step"], "eval_ppl": round(r["eval_ppl"], 3)}
            for r in eval_rows
        ],
        "tok_s_mean_first_quartile": round(sum(tps[:q]) / q, 1) if tps else None,
        "tok_s_mean_last_quartile": round(sum(tps[-q:]) / q, 1) if tps else None,
        "tok_s_min": round(min(tps), 1) if tps else None,
        "tok_s_max": round(max(tps), 1) if tps else None,
        "nonfinite_total": train_rows[-1].get("nonfinite_total") if train_rows else None,
        "resumed_steps_recovered": resume_overlap[:5] + (["..."] if len(resume_overlap) > 5 else []),
        "n_resumed_overlap_rows": len(resume_overlap),
    })
    with open(os.path.join(REPO, "ENDURANCE.json"), "w") as f:
        json.dump(evidence, f, indent=1)
    print(json.dumps(evidence, indent=1))
    return 0 if evidence.get("phase2_rc") == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
