"""Round-4 chip session runner: the measurement queue that needs the real
TPU, run serially after the endurance run frees the chip. Each phase
appends one JSON line to R4CHIP.jsonl so a crash loses nothing.

Usage: python exp_r4chip.py [phase ...]   (default: all)
Phases: remat, moe, swa, profile_hybrid, quant_eval, lra
(The decode matrix and the headline run come from bench.py itself.)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(REPO, "R4CHIP.jsonl")


def log(obj):
    line = json.dumps(obj)
    print(line, flush=True)
    with open(OUT, "a") as f:
        f.write(line + "\n")


def run(cmd, timeout=3600):
    t0 = time.time()
    p = subprocess.run(
        cmd, cwd=REPO, capture_output=True, text=True, timeout=timeout
    )
    return p.returncode, p.stdout, p.stderr, round(time.time() - t0, 1)


def phase_remat():
    rc, out, err, dt = run([sys.executable, "bench.py", "--remat-sweep"])
    log({"phase": "remat_sweep", "rc": rc, "wall_s": dt,
         "stdout": out.strip()[-4000:], "stderr_tail": err.strip()[-2000:]})


def phase_moe():
    # capacity + dropless(gmm) rows; reuses bench_train directly
    code = (
        "import json, sys; sys.path.insert(0, %r)\n"
        "import bench\n"
        "bench._enable_compile_cache()\n"
        "m = bench.bench_train(iters=8, config='moe_1b3_4e')\n"
        "print(json.dumps({'moe_capacity': m}))\n"
        "bench._free_device_memory()\n"
        "d = bench.bench_train(iters=8, config='moe_1b3_4e', moe_dropless=True)\n"
        "d['vs_capacity'] = round(d['tokens_per_sec']/m['tokens_per_sec'], 4)\n"
        "print(json.dumps({'moe_dropless_gmm': d}))\n" % REPO
    )
    rc, out, err, dt = run([sys.executable, "-c", code])
    log({"phase": "moe", "rc": rc, "wall_s": dt,
         "stdout": out.strip()[-4000:], "stderr_tail": err.strip()[-1500:]})


def phase_swa():
    rc, out, err, dt = run([sys.executable, "exp_swa_sweep.py"])
    log({"phase": "swa_sweep", "rc": rc, "wall_s": dt,
         "stdout": out.strip()[-4000:], "stderr_tail": err.strip()[-1000:]})


def phase_profile_hybrid():
    rc, out, err, dt = run(
        [sys.executable, "exp_profile.py", "hybrid_1b3", "12", "2048"]
    )
    log({"phase": "profile_hybrid", "rc": rc, "wall_s": dt,
         "stdout": out.strip()[-4000:], "stderr_tail": err.strip()[-1000:]})


def phase_quant_eval():
    # the int4 acceptance bar: held-out ppl through fp32/int8/int4 on the
    # ENDURANCE checkpoint (a genuinely trained 1.3B on the real corpus)
    ck = os.path.join(REPO, "runs", "endurance", "ckpt")
    rows = []
    for q in ("", "int8", "int4"):
        cmd = [sys.executable, "-m", "orion_tpu.evaluate",
               "--config", "lm_1b3", "--ckpt-dir", ck,
               "--data", os.path.join(REPO, "data", "val.bin"),
               "--seq-len", "2048", "--batch-size", "8",
               "--n-batches", "12"]
        if q:
            cmd += ["--quant", q]
        rc, out, err, dt = run(cmd)
        rows.append({"quant": q or "fp32", "rc": rc, "wall_s": dt,
                     "out": out.strip()[-400:],
                     "err_tail": "" if rc == 0 else err.strip()[-400:]})
    log({"phase": "quant_eval", "rows": rows})


def phase_lra():
    rows = []
    for cfgname, task, steps in [
        ("lra_listops_linear", "data/lra_sample/listops", 1500),
        ("lra_listops_softmax", "data/lra_sample/listops", 1500),
        ("lra_text_linear", "data/lra_sample/text", 1500),
        ("lra_text_softmax", "data/lra_sample/text", 1500),
    ]:
        rc, out, err, dt = run(
            [sys.executable, "-m", "orion_tpu.train_lra",
             "--config", cfgname, "--task", task,
             "--seq-len", "256", "--steps", str(steps),
             "--batch-size", "32"],
            timeout=3000,
        )
        rows.append({"config": cfgname, "task": task, "rc": rc,
                     "wall_s": dt, "out": out.strip()[-400:],
                     "err_tail": "" if rc == 0 else err.strip()[-400:]})
        log({"phase": "lra_row", **rows[-1]})
    log({"phase": "lra", "rows": rows})


PHASES = {
    "remat": phase_remat,
    "moe": phase_moe,
    "swa": phase_swa,
    "profile_hybrid": phase_profile_hybrid,
    "quant_eval": phase_quant_eval,
    "lra": phase_lra,
}


def main():
    names = sys.argv[1:] or list(PHASES)
    for n in names:
        log({"phase_start": n, "t": time.ctime()})
        try:
            PHASES[n]()
        except Exception as e:
            log({"phase": n, "error": str(e)[:400]})


if __name__ == "__main__":
    main()
